"""Huge-batch data-parallel SAE trainer with dead-feature resurrection.

Counterpart of the reference `experiments/huge_batch_size.py`: one big SAE
trained with very large batches under data parallelism, periodically
re-initializing dead dictionary features from the worst-reconstructed
examples (including the per-feature Adam-state reset, `:224-254`).

TPU-native inversion of the reference's DDP machinery (`:259-345`): no
process groups — the train step is jitted over a mesh with the batch sharded
on the "data" axis, and XLA inserts the gradient psum over ICI (SURVEY.md
§2.4 P3). Dead-feature resurrection, an in-place indexed mutation of params
AND optimizer state in torch, is a pure `tree-map`/`.at[]` update here
(SURVEY.md §7 noted this must be designed in from the start — it is: optax's
adam state mirrors param shapes, so one function handles both).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from sparse_coding__tpu.parallel.mesh import DATA_AXIS, batch_sharding
from sparse_coding__tpu.utils.faults import fault_point

Pytree = Any


@jax.tree_util.register_dataclass
@dataclass
class BigBatchState:
    params: Pytree
    buffers: Pytree
    opt_state: Pytree
    c_totals: jax.Array  # per-feature activation sums since last reinit
    step: jax.Array


class WorstExamples:
    """Track the k worst-reconstructed example indices (host-side ring of the
    reference's `worst_indices` heap, `huge_batch_size.py:208-210`)."""

    def __init__(self, k: int = 1024):
        self.k = k
        self.losses = np.full((k,), -np.inf)
        self.indices = np.zeros((k,), dtype=np.int64)

    def update(self, indices: np.ndarray, losses: np.ndarray):
        all_l = np.concatenate([self.losses, losses])
        all_i = np.concatenate([self.indices, indices])
        order = np.argsort(-all_l)[: self.k]
        self.losses, self.indices = all_l[order], all_i[order]

    def get_worst(self, n: int) -> np.ndarray:
        return self.indices[: min(n, self.k)]


def make_big_batch_step(
    sig, tx: optax.GradientTransformation, l1_warmup_steps: int = 0
):
    """Fused single-model step: grads + optimizer + code-activity totals.
    Data parallelism comes from the CALLER placing the batch with a "data"-axis
    sharding (`train_big_batch` does) — the jitted step then partitions and
    XLA inserts the gradient psum.

    ``l1_warmup_steps > 0`` ramps the ``l1_alpha`` buffer linearly from ~0 to
    its configured value over that many steps (a trace-time branch — the ramp
    is computed from ``state.step`` inside the jit, so one compiled program
    serves the whole schedule). Rationale: the round-3 LR_COLLAPSE study
    showed the l1-pressure x Adam-lr dynamic kills features fastest at the
    START of training, when reconstruction gradients are weakest; the
    reference has no equivalent knob."""

    grad_fn = jax.grad(sig.loss, has_aux=True)

    @partial(jax.jit, donate_argnums=(0,))
    def step(state: BigBatchState, batch: jax.Array):
        # shared schedule + error policy (raises on missing l1_alpha,
        # ADVICE r4): sparse_coding__tpu.ensemble.l1_warmup_buffers
        from sparse_coding__tpu.ensemble import l1_warmup_buffers

        buffers = l1_warmup_buffers(
            state.buffers, state.step, l1_warmup_steps, sig
        )
        grads, (loss_dict, aux) = grad_fn(state.params, buffers, batch)
        updates, opt_state = tx.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        c = aux["c"]
        c_totals = state.c_totals + (c != 0).sum(axis=0)
        # per-example MSE for worst-example tracking (reference `:196-199`)
        # recompute decode from the *code* — cheap vs the grad pass
        new_state = BigBatchState(
            params=params,
            buffers=state.buffers,
            opt_state=opt_state,
            c_totals=c_totals,
            step=state.step + 1,
        )
        return new_state, loss_dict, c

    return step


def per_example_mse_from_codes(sig, params, buffers, batch, c) -> jax.Array:
    """[B] reconstruction error per example, decoding the codes the train
    step already computed (no second encode forward)."""
    ld = sig.to_learned_dict(params, buffers)
    x_hat = ld.uncenter(ld.decode(c))
    return ((x_hat - batch) ** 2).mean(axis=-1)


def resurrect_dead_features(
    state: BigBatchState,
    replacement_vectors: jax.Array,
    encoder_key: str = "encoder",
    encoder_norm_ratio: float = 0.2,
    threshold: int = 0,
) -> Tuple[BigBatchState, int]:
    """Re-init features with `c_totals <= threshold` from the worst-recon
    examples; zero their Adam moments; reset activity counters.

    Pure counterpart of reference `huge_batch_size.py:224-254`. All features
    with count ≤ threshold are rewritten via a masked `jnp.where` — fixed
    shapes, jit-safe. `replacement_vectors` is `[n_feats, d]` (rows for live
    features are ignored; callers tile the worst examples to n_feats rows).

    Deliberate fix vs the reference's `worst.T * ratio / av_norm`
    (`huge_batch_size.py:240`, which never normalizes the example, so the new
    row's norm scales with the ACTIVATION's magnitude): here the replacement
    is normalized to `ratio x` the average encoder-row norm — the stated
    intent of worst-example resurrection.
    """
    dead = state.c_totals <= threshold
    n_dead = int(jax.device_get(dead.sum()))

    enc = state.params[encoder_key]
    av_norm = jnp.linalg.norm(enc, axis=-1).mean()
    scale = encoder_norm_ratio * av_norm / jnp.clip(
        jnp.linalg.norm(replacement_vectors, axis=-1, keepdims=True), 1e-8, None
    )
    new_enc = jnp.where(dead[:, None], replacement_vectors * scale, enc)

    params = dict(state.params)
    params[encoder_key] = new_enc
    if "encoder_bias" in params:
        params["encoder_bias"] = jnp.where(dead, 0.0, params["encoder_bias"])

    def reset_moments(leaf, ref_leaf):
        # zero adam mu/nu rows of dead features wherever the leaf mirrors a
        # param with leading n_feats dim
        if hasattr(leaf, "shape") and leaf.shape[:1] == dead.shape:
            expand = dead.reshape((-1,) + (1,) * (leaf.ndim - 1))
            return jnp.where(expand, 0.0, leaf)
        return leaf

    opt_state = jax.tree.map(lambda l: reset_moments(l, None), state.opt_state)
    return (
        BigBatchState(
            params=params,
            buffers=state.buffers,
            opt_state=opt_state,
            c_totals=jnp.zeros_like(state.c_totals),
            step=state.step,
        ),
        n_dead,
    )


def train_big_batch(
    sig,
    init_hparams: Dict[str, Any],
    dataset: jax.Array,
    batch_size: int,
    n_steps: int,
    key: jax.Array,
    learning_rate: float = 1e-3,
    mesh=None,
    reinit_every: Optional[int] = 100,
    worst_k: int = 1024,
    compute_dtype=None,
    resurrection_log: Optional[list] = None,
    encoder_norm_ratio: float = 0.2,
    l1_warmup_steps: int = 0,
    telemetry=None,
    trace_trigger=None,
    checkpoint_dir: Optional[str] = None,
    resume: Optional[bool] = None,
    checkpoint_every: Optional[int] = None,
    checkpoint_keep: int = 3,
    preempt_sync_every: int = 16,
) -> Tuple[BigBatchState, Any]:
    """Train one SAE with huge data-parallel batches + periodic dead-feature
    resurrection. Returns (final state, sig) for `to_learned_dict` export.

    ``compute_dtype`` bakes a matmul precision (e.g. ``jnp.bfloat16``) into
    the step trace via `utils.precision` — same master-weights policy as
    `Ensemble`. ``resurrection_log`` (a caller-owned list) receives one
    ``(step, n_dead)`` tuple per resurrection event. ``encoder_norm_ratio``
    scales re-initialized encoder rows relative to the average live-row norm
    (the reference's convention is 0.2, `huge_batch_size.py:240`; RESURRECT_r04
    measures that transplant at the 32x flagship shape). ``l1_warmup_steps``
    linearly ramps l1 pressure from ~0 (see `make_big_batch_step`).
    ``telemetry`` (a `telemetry.events.RunTelemetry`) additionally records
    each resurrection as a structured event plus step/resurrection counters
    — the artifact-side trail the RESURRECT_r04 studies had to reconstruct
    from stdout. ``trace_trigger`` (a `telemetry.profiling.TraceTrigger`)
    is stepped once per train step (host-side integer compares only), so
    env-armed `SC_TRACE_WINDOW` profiler windows resolve at true step
    granularity here; HBM watermark gauges are sampled at each resurrection
    boundary and at the end of training.

    Preemption safety (docs/RECOVERY.md): when ``checkpoint_dir`` is set the
    run survives being killed at any instant — SIGTERM/SIGINT triggers a
    crash-consistent checkpoint (full `BigBatchState` + step cursor + RNG
    key) at the next step boundary and a resumable exit (code 75);
    ``checkpoint_every=N`` additionally checkpoints every N steps, keeping
    the newest ``checkpoint_keep``. ``resume=True`` (or ``SC_RESUME=1``)
    restores the latest committed checkpoint and replays the remaining
    steps with the original key chain. The host-side worst-example ring
    restarts empty on resume (its ~`reinit_every`-step window refills
    before the next resurrection); on pods the preemption agreement
    exchange runs every ``preempt_sync_every`` step boundaries.

    ``dataset`` may also be a chunk-store folder (or `data.ChunkStore`):
    the store is loaded through `data.chunks.load_store_dataset`, which
    verifies every chunk against its commit manifest (``SC_CHUNK_VERIFY``),
    quarantines corruption, and skips lost chunks in degraded mode within
    ``SC_CHUNK_LOSS_BUDGET`` — past the budget it raises `ResumableAbort`
    (exit 75) instead of training on bad rows (docs/DATAPLANE.md).
    """
    from sparse_coding__tpu.utils import precision as px

    if not hasattr(dataset, "shape"):
        # a chunk store (folder path or ChunkStore): degraded-mode load —
        # the big-batch trainer samples rows, so a skipped chunk simply
        # shrinks the pool; the budget bounds how much may go missing
        from sparse_coding__tpu.data.chunks import load_store_dataset
        from sparse_coding__tpu.telemetry.spans import span as _span

        with _span(telemetry, "data_wait", name="load_store_dataset"):
            dataset, _budget = load_store_dataset(dataset, telemetry=telemetry)
    with px.compute(compute_dtype):
        return _train_big_batch(
            sig, init_hparams, dataset, batch_size, n_steps, key,
            learning_rate, mesh, reinit_every, worst_k, resurrection_log,
            encoder_norm_ratio, l1_warmup_steps, telemetry, trace_trigger,
            checkpoint_dir, resume, checkpoint_every, checkpoint_keep,
            preempt_sync_every,
        )


def _train_big_batch(
    sig, init_hparams, dataset, batch_size, n_steps, key,
    learning_rate, mesh, reinit_every, worst_k, resurrection_log,
    encoder_norm_ratio, l1_warmup_steps, telemetry=None, trace_trigger=None,
    checkpoint_dir=None, resume=None, checkpoint_every=None,
    checkpoint_keep=3, preempt_sync_every=16,
) -> Tuple[BigBatchState, Any]:
    if trace_trigger is None:
        # existing callers (resurrect/batch-scaling studies) pass no trigger:
        # honor the documented SC_TRACE_WINDOW env workflow for them too —
        # an unarmed trigger costs one int compare per step
        from sparse_coding__tpu.telemetry.profiling import TraceTrigger

        trace_trigger = TraceTrigger.from_env(telemetry=telemetry)
    k_init, key = jax.random.split(key)
    params, buffers = sig.init(k_init, **init_hparams)
    tx = optax.adam(learning_rate)
    n_feats = params["encoder"].shape[0]
    state = BigBatchState(
        params=params,
        buffers=buffers,
        opt_state=tx.init(params),
        c_totals=jnp.zeros((n_feats,)),
        step=jnp.zeros((), jnp.int32),
    )

    # checkpoint/resume/preemption glue (docs/RECOVERY.md): shared with the
    # sweep drivers via train.loop.DriverCheckpointer
    ckpt = None
    start_step = 0
    if checkpoint_dir is not None:
        from sparse_coding__tpu.train.loop import DriverCheckpointer
        from sparse_coding__tpu.train.preemption import resume_requested

        ckpt = DriverCheckpointer(
            checkpoint_dir, telemetry=telemetry, keep=checkpoint_keep,
            every=checkpoint_every, sync_every=preempt_sync_every,
        )
        if resume_requested(resume):
            template = {
                "cursor": {"step": 0, "key": np.zeros((2,), np.uint32)},
                "state": state,
            }
            tree = ckpt.restore(template)
            if tree is not None:
                state = tree["state"]
                start_step = int(tree["cursor"]["step"])
                key = jnp.asarray(np.asarray(tree["cursor"]["key"]))
                print(f"Resumed {checkpoint_dir} at step {start_step}")
    if mesh is not None:
        sharding = batch_sharding(mesh)
        # mesh-dependent loss specialization (e.g. the tied-SAE DP backward
        # that halves gradient all-reduce wire — models/sae.py:_tied_pair_dp);
        # execution-only: the returned sig for export stays the plain one
        if hasattr(sig, "bind_mesh"):
            sig_exec = sig.bind_mesh(mesh)
        else:
            sig_exec = sig
    else:
        sig_exec = sig
    step_fn = make_big_batch_step(sig_exec, tx, l1_warmup_steps=l1_warmup_steps)
    mse_fn = jax.jit(partial(per_example_mse_from_codes, sig))

    worst = WorstExamples(worst_k)
    n = dataset.shape[0]
    # goodput: per-step spans would be noise — one "step" span per window
    # between host-sync boundaries (resurrections, end of run); checkpoint
    # saves inside the window are subtracted by the ledger's innermost-wins
    # sweep, so nothing is double-counted
    from sparse_coding__tpu.telemetry.spans import span as _span

    win = _span(telemetry, "step", name="step_window").begin()
    win_start = start_step
    try:
        for i in range(start_step, n_steps):
            fault_point("step_loop", step=i)
            key, k = jax.random.split(key)
            idxs = np.asarray(jax.random.randint(k, (batch_size,), 0, n))
            batch = dataset[idxs]
            if mesh is not None:
                batch = jax.device_put(batch, sharding)
            state, loss_dict, c = step_fn(state, batch)
            if reinit_every:
                # worst-example tracking (host sync) only if resurrection is
                # on; decodes the codes the step already produced
                mses = np.asarray(
                    jax.device_get(mse_fn(state.params, state.buffers, batch, c))
                )
                worst.update(idxs, mses)

            if reinit_every and (i + 1) % reinit_every == 0:
                win.end(steps=i + 1 - win_start)
                win_start = i + 1
                worst_idx = worst.get_worst(n_feats)
                reps = dataset[np.resize(worst_idx, n_feats)]
                state, n_dead = resurrect_dead_features(
                    state, jnp.asarray(reps),
                    encoder_norm_ratio=encoder_norm_ratio,
                )
                worst = WorstExamples(worst_k)
                if resurrection_log is not None:
                    resurrection_log.append((i + 1, n_dead))
                if telemetry is not None:
                    telemetry.event(
                        "resurrection", step=i + 1, n_dead=int(n_dead),
                        n_feats=int(n_feats),
                    )
                    telemetry.counter_inc("resurrections")
                    telemetry.counter_inc("resurrected_features", int(n_dead))
                    # resurrection is already a host-sync boundary: cheap
                    # spot for an HBM watermark sample + pod heartbeat
                    # (skew window = wall since the previous heartbeat;
                    # no-op single-host)
                    from sparse_coding__tpu.telemetry.multihost import heartbeat
                    from sparse_coding__tpu.telemetry.profiling import record_hbm_watermarks

                    record_hbm_watermarks(telemetry)
                    heartbeat(telemetry, step=i + 1)
                if n_dead:
                    print(f"step {i+1}: resurrected {n_dead} dead features")
                win = _span(telemetry, "step", name="step_window").begin()
            if telemetry is not None:
                telemetry.counter_inc("train.steps")
            trace_trigger.on_step(i + 1)  # host-side int compares only
            if ckpt is not None:
                # step-window boundary: cursor = completed steps + the
                # post-split key (a resumed run replays the same batches).
                # Unflagged single-host cost: one bool read.
                def _save_ckpt(path, _done=i + 1):
                    from sparse_coding__tpu.train.checkpoint import save_checkpoint_tree

                    save_checkpoint_tree(path, {
                        "cursor": {
                            "step": _done,
                            "key": np.asarray(jax.device_get(key)),
                        },
                        "state": state,
                    })

                ckpt.boundary(i + 1, _save_ckpt)
        if telemetry is not None:
            from sparse_coding__tpu.telemetry.multihost import heartbeat
            from sparse_coding__tpu.telemetry.profiling import record_hbm_watermarks

            record_hbm_watermarks(telemetry)
            heartbeat(telemetry, step=n_steps)
    finally:
        # an exception mid-run must still finalize any in-flight profiler
        # window — a leaked trace blocks every later capture in the process
        win.end()  # the open step window: emitted even on preempt/crash
        trace_trigger.close(n_steps)
        if ckpt is not None:
            ckpt.close()  # no longer polling: signals terminate normally
    return state, sig
