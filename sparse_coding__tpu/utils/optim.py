"""Adam with compressed second-moment storage (``nu_dtype``) via stochastic rounding.

Why this exists (THROUGHPUT.md §r4c): the fused tied-SAE train step is
memory-bound on its parameter/optimizer stream — params 134 MB + Adam moments
268 MB read+write per step at the bench shape. optax ships ``mu_dtype`` (first
moment in bf16, adopted in r4c for +6%) but has NO ``nu_dtype``, and naively
storing ``nu`` in bf16 with round-to-nearest is genuinely unsafe, for two
distinct reasons this module is built to avoid:

1. **EMA-horizon corruption**: optax's ``update_moment_per_elem_norm`` runs the
   decay multiply in the storage dtype (weak typing), so a bf16-stored ``nu``
   would round ``b2 = 0.999`` to bf16 ``0.99609``, silently changing the EMA
   horizon from 1000 to ~256 steps. Here the EMA is ALWAYS computed in fp32
   (``b2·nu + (1-b2)·g²`` with ``nu`` upcast) and only the *storage* is
   compressed.
2. **Round-to-nearest freeze**: the per-step increment ``(1-b2)(g² - nu)`` is
   ~0.1% of ``nu`` while a bf16 ulp is ~0.8% of ``nu`` — with deterministic
   rounding the stored value re-rounds to itself and the second moment FREEZES
   once it is within ~4× of g² (test_optim.py demonstrates the freeze).
   Stochastic rounding makes each store unbiased, so the EMA tracks in
   expectation with ~0.2% relative storage noise (≈0.1% on the ``sqrt(nu)``
   denominator — per-parameter lr jitter far below Adam's own noise floor).

The fused Pallas kernel mirrors this contract with the on-core PRNG
(`ops/tied_sae_kernel.py:_bwd_adam_kernel`); this module is the XLA/CPU path
and the reference semantics.

The reference framework has no counterpart (torchopt adam keeps fp32 moments;
`/root/reference/autoencoders/ensemble.py:85-95` inits torchopt state) — this
is a TPU-HBM-bandwidth optimization with measured loss parity.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import optax

_MASK16 = jnp.uint32(0xFFFF)


def stochastic_round(x: jax.Array, key: jax.Array, dtype) -> jax.Array:
    """Unbiasedly round fp32 ``x`` to ``dtype`` (bf16) using randomness from ``key``.

    Classic bit trick: add 16 uniform random low bits to the fp32 bit pattern
    and truncate to the upper 16 (bf16 is fp32's upper half). The carry from
    the mantissa add performs the round-up with probability equal to the
    truncated fraction, so ``E[round(x)] = x`` exactly for finite values.
    Non-finite values pass through a plain cast (bit-pattern adds would
    corrupt inf/nan).
    """
    dtype = jnp.dtype(dtype)
    if dtype != jnp.bfloat16:
        raise ValueError(f"stochastic_round targets bfloat16, got {dtype}")
    xf = x.astype(jnp.float32)
    bits = jax.random.bits(key, xf.shape, jnp.uint32) & _MASK16
    xb = jax.lax.bitcast_convert_type(xf, jnp.uint32)
    up = ((xb + bits) >> 16).astype(jnp.uint16)
    out = jax.lax.bitcast_convert_type(up, jnp.bfloat16)
    return jnp.where(jnp.isfinite(xf), out, xf.astype(jnp.bfloat16))


def scale_by_adam_compressed(
    b1: float = 0.9,
    b2: float = 0.999,
    eps: float = 1e-8,
    eps_root: float = 0.0,
    mu_dtype=None,
    nu_dtype=None,
    seed: int = 0,
) -> optax.GradientTransformation:
    """`optax.scale_by_adam` + a ``nu_dtype`` storage policy (see module doc).

    Bit-compatibility contract:
      - ``nu_dtype=None`` → the update math IS optax's (same expressions, same
        python-float complements); only code identity differs.
      - ``mu_dtype`` follows optax exactly (decay multiply in storage dtype,
        cast-back at the end) so existing mu_dtype=bf16 numbers carry over.
      - ``nu_dtype=bfloat16`` → fp32 EMA + bias-corrected update from the
        UNROUNDED fp32 value; only the carried state is stochastically rounded.
        The rounding stream is derived from (seed, step) — deterministic given
        the seed, and NOT correlated step-to-step. State layout stays
        `optax.ScaleByAdamState`, so checkpoints/fused-kernel plumbing that
        read ``.count/.mu/.nu`` keep working.
    """
    mu_dtype = None if mu_dtype is None else jnp.dtype(mu_dtype)
    nu_dtype = None if nu_dtype is None else jnp.dtype(nu_dtype)
    if nu_dtype not in (None, jnp.dtype(jnp.float32), jnp.dtype(jnp.bfloat16)):
        raise ValueError(f"nu_dtype must be None/float32/bfloat16, got {nu_dtype}")

    def init_fn(params):
        mu = jax.tree.map(lambda p: jnp.zeros_like(p, dtype=mu_dtype or p.dtype), params)
        nu = jax.tree.map(lambda p: jnp.zeros_like(p, dtype=nu_dtype or p.dtype), params)
        return optax.ScaleByAdamState(count=jnp.zeros([], jnp.int32), mu=mu, nu=nu)

    def update_fn(updates, state, params=None):
        del params
        # mu: optax's update_moment expression verbatim (storage-dtype decay
        # multiply under weak typing — bit parity with optax mu_dtype runs)
        mu = jax.tree.map(lambda g, t: (1 - b1) * g + b1 * t, updates, state.mu)
        # nu: fp32 EMA regardless of storage dtype (reason 1 in module doc)
        nu = jax.tree.map(
            lambda g, t: (1 - b2) * jnp.square(g.astype(jnp.float32))
            + b2 * t.astype(jnp.float32),
            updates,
            state.nu,
        )
        # optax renamed safe_int32_increment -> safe_increment; this image's
        # optax only has the old name
        count_inc = getattr(
            optax, "safe_increment", getattr(optax, "safe_int32_increment", None)
        )(state.count)
        tf = count_inc.astype(jnp.float32)
        bc1 = 1 - jnp.power(jnp.float32(b1), tf)
        bc2 = 1 - jnp.power(jnp.float32(b2), tf)
        new_updates = jax.tree.map(
            lambda m, v: (m / bc1) / (jnp.sqrt(v / bc2 + eps_root) + eps), mu, nu
        )
        mu = jax.tree.map(lambda t: t.astype(mu_dtype) if mu_dtype else t, mu)
        if nu_dtype == jnp.bfloat16:
            # one key per step; leaves decorrelated by fold_in(leaf index).
            # Under the ensemble's vmap all members share `count`, so members
            # share a bit stream — harmless: their nu VALUES differ, so the
            # rounding outcomes are independent where it matters.
            key = jax.random.fold_in(jax.random.PRNGKey(seed), count_inc)
            leaves, treedef = jax.tree.flatten(nu)
            leaves = [
                stochastic_round(leaf, jax.random.fold_in(key, i), jnp.bfloat16)
                for i, leaf in enumerate(leaves)
            ]
            nu = jax.tree.unflatten(treedef, leaves)
        elif nu_dtype is not None:
            nu = jax.tree.map(lambda t: t.astype(nu_dtype), nu)
        return new_updates, optax.ScaleByAdamState(count=count_inc, mu=mu, nu=nu)

    return optax.GradientTransformation(init_fn, update_fn)


def adam(
    learning_rate: float = 1e-3,
    b1: float = 0.9,
    b2: float = 0.999,
    eps: float = 1e-8,
    mu_dtype=None,
    nu_dtype=None,
    seed: int = 0,
) -> optax.GradientTransformation:
    """Drop-in `optax.adam` with the extra ``nu_dtype`` knob.

    ``nu_dtype=None`` returns literal `optax.adam` (bit-identical programs and
    shared-step cache identity); ``nu_dtype='bfloat16'`` swaps in
    `scale_by_adam_compressed`. This is what `ensemble.optim_str_to_func`
    resolves ``"adam"`` to.
    """
    if nu_dtype is None:
        return optax.adam(learning_rate, b1=b1, b2=b2, eps=eps, mu_dtype=mu_dtype)
    return optax.chain(
        scale_by_adam_compressed(
            b1=b1, b2=b2, eps=eps, mu_dtype=mu_dtype, nu_dtype=nu_dtype, seed=seed
        ),
        optax.scale_by_learning_rate(learning_rate),
    )
