"""Quantified pod scale-out model: collective traffic + v4-32 projection.

VERDICT r3 next #2: the ≥3×/chip north star (BASELINE.json) was a pod-scale-out
*story* with zero numbers attached. This script attaches the numbers this
environment can produce:

1. **Measured collective traffic.** For each relevant mesh factorization of a
   16-device virtual CPU mesh (v4-32 = 16 chips: v4 TensorCores are
   megacore-fused, one JAX device per chip), compile the REAL sharded train
   step — the same `Ensemble.shard` + jit program a pod would run (the
   dryrun's path; only `jax.distributed.initialize` differs) — and read the
   per-step collective operations straight out of the optimized SPMD HLO:
   op counts, shard bytes, and the ring-model wire bytes per chip implied by
   each op's replica-group size. XLA's own `cost_analysis` flops/bytes are
   recorded alongside.

   Workloads:
     - config 2 (the bench headline): 8-member tied-SAE l1 sweep,
       512 → 4096, batch 2048/step — `big_sweep_experiments.py:295-341`.
     - config 5 (the pod workload): 4-member tied-SAE ensemble at 32×
       overcomplete (1024 → 32768), batch 2048 — `:546-644` + BASELINE
       config 5, the shape `scripts/dictpar_run.py` trains for real.

2. **Analytic weak-scaling projection** (`project()`): combine the measured
   single-chip v5e step time (BENCH/THROUGHPUT) with the HLO-measured wire
   bytes and public v4 constants (peak bf16 FLOP/s, ICI link bandwidth,
   torus axes) into predicted acts/s/chip at 16 chips, with a ±2× ICI
   bandwidth sensitivity band — the conclusion must not hinge on the exact
   link constant. No-overlap (conservative) and full-overlap (optimistic)
   bounds are both reported.

Writes SCALEOUT_<round>.json at the repo root. Run time: a few minutes of
CPU compiles; no TPU needed (and none used — safe to run alongside chip jobs).
"""

from __future__ import annotations

import json
import os
import re
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
ROUND_TAG = os.environ.get("PARITY_ROUND", "r04")

if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

N_VIRTUAL_DEVICES = 16  # v4-32 slice = 16 megacore chips

# -- public hardware constants (assumptions stated in the artifact) ----------
V4 = dict(
    name="TPU v4 (v4-32 slice, 16 chips, 3D torus)",
    peak_bf16_flops=275e12,
    hbm_bytes_per_sec=1.2e12,
    # ICI: one-way bandwidth per link. v4 runs a 3D torus; a collective over
    # one mesh axis rides that axis's bidirectional ring = 2 links.
    ici_link_oneway_bytes_per_sec=4.5e10,
    links_per_axis=2,  # bidirectional ring on the axis
)
V5P = dict(
    name="TPU v5p (16 chips)",
    peak_bf16_flops=459e12,
    hbm_bytes_per_sec=2.8e12,
    ici_link_oneway_bytes_per_sec=9.0e10,
    links_per_axis=2,
)

# measured on the single v5e chip (BENCH_r03 / THROUGHPUT.md): the headline
# step sustains MFU ~0.74 on its matmul FLOPs; projections assume the same
# achieved MFU transfers to v4 (same XLA program, same operand shapes).
MEASURED_SINGLE_CHIP = dict(
    device="TPU v5 lite",
    peak_bf16_flops=197e12,
    headline_acts_per_sec=871_187.0,  # driver-captured BENCH_r03 (median r4 may differ)
    mfu=0.742,
)

_DTYPE_BYTES = {
    "f64": 8, "f32": 4, "bf16": 2, "f16": 2, "f8": 1,
    "s64": 8, "s32": 4, "s16": 2, "s8": 1, "u64": 8, "u32": 4, "u16": 2,
    "u8": 1, "pred": 1,
}

_COLLECTIVES = (
    "all-reduce", "all-gather", "reduce-scatter", "all-to-all",
    "collective-permute",
)


def _shape_bytes(shape_str: str) -> int:
    """Bytes of an HLO shape string like 'f32[8,512,4096]{...}' or a tuple
    '(f32[8], f32[8])'."""
    total = 0
    for m in re.finditer(r"(\w+)\[([0-9,]*)\]", shape_str):
        dt, dims = m.group(1), m.group(2)
        if dt not in _DTYPE_BYTES:
            continue
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        total += n * _DTYPE_BYTES[dt]
    return total


def _group_size(line: str, n_devices: int) -> int:
    """Participants per replica group of a collective HLO line."""
    m = re.search(r"replica_groups=\{\{([^}]*)\}", line)
    if m:
        return len([t for t in m.group(1).split(",") if t.strip() != ""])
    m = re.search(r"replica_groups=\[(\d+),(\d+)\]", line)  # iota form [n,g]
    if m:
        return int(m.group(2))
    return n_devices


def collective_traffic(hlo_text: str, n_devices: int) -> dict:
    """Per-step collective inventory from optimized SPMD HLO.

    Wire bytes per chip use the standard ring models (scaling-book):
      all-reduce:      2 * (g-1)/g * shard_bytes   (reduce-scatter+all-gather)
      all-gather:      (g-1)/g * gathered_bytes    (output shape is gathered)
      reduce-scatter:  (g-1)/g * input_bytes ≈ (g-1) * shard_bytes
      all-to-all:      (g-1)/g * bytes
      collective-permute: bytes (one hop)
    """
    ops = []
    wire_total = 0.0
    for line in hlo_text.splitlines():
        s = line.strip()
        # async collectives come as -start/-done pairs: count -start (it
        # carries the op + shapes), never -done (same traffic, second match
        # would double-count). Sync forms have the name followed by "(".
        m = re.match(r"(?:ROOT )?%?[\w.\-]+ = (.*?) (all-reduce|all-gather|"
                     r"reduce-scatter|all-to-all|collective-permute)"
                     r"(-start)?\(", s)
        if not m:
            continue
        out_shape, kind = m.group(1), m.group(2)
        b = _shape_bytes(out_shape)
        g = _group_size(s, n_devices)
        if g <= 1:
            wire = 0.0
        elif kind == "all-reduce":
            wire = 2 * (g - 1) / g * b
        elif kind == "all-gather":
            wire = (g - 1) / g * b
        elif kind == "reduce-scatter":
            wire = (g - 1) * b  # b is the scattered (output) shard
        elif kind == "all-to-all":
            wire = (g - 1) / g * b
        else:  # collective-permute
            wire = float(b)
        ops.append({"op": kind, "out_bytes": b, "group_size": g,
                    "wire_bytes_per_chip": round(wire)})
        wire_total += wire
    summary = {}
    for o in ops:
        k = o["op"]
        summary.setdefault(k, {"count": 0, "wire_bytes_per_chip": 0})
        summary[k]["count"] += 1
        summary[k]["wire_bytes_per_chip"] += o["wire_bytes_per_chip"]
    return {
        "ops": ops,
        "summary": summary,
        "wire_bytes_per_chip_per_step": round(wire_total),
    }


def compile_case(name, n_models, d_act, n_dict, batch, mesh_shape, note=""):
    """Build the real sharded ensemble step, compile it for the virtual mesh,
    and extract collective traffic + XLA cost analysis."""
    import jax
    import jax.numpy as jnp

    from sparse_coding__tpu import build_ensemble
    from sparse_coding__tpu.models import FunctionalTiedSAE
    from sparse_coding__tpu.parallel import make_mesh

    model, data, dict_ = mesh_shape
    t0 = time.time()
    ens = build_ensemble(
        FunctionalTiedSAE,
        jax.random.PRNGKey(0),
        [{"l1_alpha": 10 ** (-4 + i * 0.25)} for i in range(n_models)],
        optimizer_kwargs={"learning_rate": 3e-4},
        activation_size=d_act,
        n_dict_components=n_dict,
    )
    mesh = make_mesh(model, data, dict_)
    ens.shard(mesh)
    from sparse_coding__tpu.parallel.mesh import batch_sharding

    batch_arr = jax.device_put(
        jax.random.normal(jax.random.PRNGKey(1), (batch, d_act)),
        batch_sharding(mesh),
    )
    lowered = ens._step.lower(ens.state, batch_arr)
    compiled = lowered.compile()
    hlo = compiled.as_text()
    traffic = collective_traffic(hlo, N_VIRTUAL_DEVICES)
    try:
        ca = compiled.cost_analysis()
        ca = ca[0] if isinstance(ca, (list, tuple)) else ca
        cost = {
            "flops_per_step_per_chip": float(ca.get("flops", float("nan"))),
            "hbm_bytes_per_step_per_chip": float(
                ca.get("bytes accessed", float("nan"))
            ),
        }
    except Exception as e:  # cost_analysis is best-effort across backends
        cost = {"error": repr(e)}
    try:
        mem = compiled.memory_analysis()
        cost["argument_bytes_per_chip"] = int(mem.argument_size_in_bytes)
        cost["temp_bytes_per_chip"] = int(mem.temp_size_in_bytes)
    except Exception:
        pass
    # analytic matmul FLOPs of the tied-SAE step (5 matmul passes), whole step
    flops_step_total = n_models * 5 * 2 * d_act * n_dict * batch
    case = {
        "name": name,
        "note": note,
        "workload": {
            "n_models": n_models, "d_act": d_act, "n_dict": n_dict,
            "batch_per_step": batch,
        },
        "mesh": {"model": model, "data": data, "dict": dict_},
        "matmul_flops_per_step_total": flops_step_total,
        "matmul_flops_per_step_per_chip": flops_step_total // N_VIRTUAL_DEVICES,
        "collectives": traffic,
        "xla_cost_analysis": cost,
        "compile_seconds": round(time.time() - t0, 1),
    }
    del ens
    return case


def project(case: dict, hw: dict, mfu: float) -> dict:
    """Weak-scaling projection for one compiled case on `hw`.

    T_compute = matmul FLOPs per chip / (mfu * peak); T_ici = wire bytes per
    chip / (links_per_axis * link bandwidth). Efficiency bounds: no-overlap
    (serialize compute+comm) and full-overlap (max of the two). The ±2×
    bandwidth band shows whether the conclusion survives the ICI constant
    being off."""
    flops_chip = case["matmul_flops_per_step_per_chip"]
    wire = case["collectives"]["wire_bytes_per_chip_per_step"]
    batch = case["workload"]["batch_per_step"]
    t_compute = flops_chip / (mfu * hw["peak_bf16_flops"])
    out = {"hardware": hw["name"], "assumed_mfu": mfu}
    for tag, scale in [("ici_x1", 1.0), ("ici_x0.5", 0.5), ("ici_x2", 2.0)]:
        bw = hw["links_per_axis"] * hw["ici_link_oneway_bytes_per_sec"] * scale
        t_ici = wire / bw
        t_no_overlap = t_compute + t_ici
        t_overlap = max(t_compute, t_ici)
        out[tag] = {
            "t_compute_us": round(t_compute * 1e6, 1),
            "t_ici_us": round(t_ici * 1e6, 1),
            "comm_fraction_no_overlap": round(t_ici / t_no_overlap, 4),
            # whole-step batch / whole-step time, divided over the chips
            "acts_per_sec_per_chip_no_overlap": round(
                batch / t_no_overlap / N_VIRTUAL_DEVICES
            ),
            "acts_per_sec_per_chip_overlap": round(
                batch / t_overlap / N_VIRTUAL_DEVICES
            ),
        }
    return out


def main():
    # force the virtual CPU mesh BEFORE backend init; never touches the TPU
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count={N_VIRTUAL_DEVICES}"
    ).strip()
    import jax

    jax.config.update("jax_platforms", "cpu")

    cases = [
        # config 2 — the bench headline, pod-fanned. Sweep members are
        # embarrassingly parallel: a pure model-axis mesh must carry ZERO
        # per-step collectives (the assert below holds the HLO to it).
        compile_case(
            "config2_sweep_fanout", 16, 512, 4096, 2048,
            (16, 1, 1),
            note="16-member l1 sweep, one member per chip, batch replicated; "
            "the pod analogue of the reference's process-per-GPU dispatch",
        ),
        # config 2 — hybrid fan-out x data parallelism: each 2-chip data
        # group all-reduces its members' gradients every step.
        compile_case(
            "config2_hybrid_dp2", 16, 512, 4096, 2048 * 2,
            (8, 2, 1),
            note="16 members over 8 model-shards x data 2: the per-step "
            "gradient all-reduce a data axis buys",
        ),
        # config 2 — pure data parallelism (the DDP shape): gradient psum of
        # all 8 members' params every step. The anti-pattern to quantify.
        compile_case(
            "config2_pure_dp", 8, 512, 4096, 2048 * 16,
            (1, 16, 1),
            note="8-member ensemble replicated, batch sharded 16-way: "
            "per-step gradient all-reduce of every parameter",
        ),
        # config 5 — dict-parallel pod workload (dictpar_run.py's shape).
        compile_case(
            "config5_dictpar", 4, 1024, 32768, 2048 * 4,
            (1, 4, 4),
            note="4-member 32x-overcomplete ensemble, dict sharded 4-way x "
            "data 4-way (BASELINE config 5)",
        ),
        # config 5 — same workload, model+data only (no dict sharding).
        compile_case(
            "config5_model_data", 4, 1024, 32768, 2048 * 4,
            (4, 4, 1),
            note="members on the model axis instead: what dict sharding buys "
            "or costs vs pure fan-out at the same chip count",
        ),
    ]

    projections = {}
    for case in cases:
        projections[case["name"]] = {
            "v4": project(case, V4, MEASURED_SINGLE_CHIP["mfu"]),
            "v5p": project(case, V5P, MEASURED_SINGLE_CHIP["mfu"]),
        }

    # headline per-chip ceiling math against the A100 analytic baseline
    # (bench.py: 0.78e6 acts/s at 6-matmul-pass accounting; our step does 5)
    a100 = 0.78e6
    base_flops_per_act = 8 * 5 * 2 * 512 * 4096  # config-2 matmul work
    ceilings = {}
    for hw, mfu_pts in [(V4, (MEASURED_SINGLE_CHIP["mfu"], 0.85, 1.0)),
                        (V5P, (MEASURED_SINGLE_CHIP["mfu"], 0.85, 1.0))]:
        ceilings[hw["name"]] = {
            f"mfu_{m}": round(
                m * hw["peak_bf16_flops"] / base_flops_per_act / a100, 2
            )
            for m in mfu_pts
        }
    measured = MEASURED_SINGLE_CHIP | {
        "vs_baseline": round(
            MEASURED_SINGLE_CHIP["headline_acts_per_sec"] / a100, 3
        )
    }

    report = {
        "round": ROUND_TAG,
        "method": (
            "Real sharded train-step programs (Ensemble.shard + jit, the "
            "dryrun path) compiled for a 16-device virtual CPU mesh; "
            "collective ops, replica groups and shard bytes parsed from the "
            "optimized SPMD HLO; ring-model wire bytes per chip; analytic "
            "projection = measured-MFU compute time + wire/ICI time. "
            "Multi-chip hardware is unreachable from this environment "
            "(BASELINE.md), so these are the strongest numbers available "
            "in-image: the program is the real one, the wire bytes are "
            "measured, only the link-rate constants are assumed (with a "
            "±2x sensitivity band)."
        ),
        "measured_single_chip": measured,
        "hardware_constants": {"v4": V4, "v5p": V5P},
        "cases": cases,
        "projections": projections,
        "per_chip_ceiling_vs_a100_baseline": {
            "explanation": (
                "acts/s/chip is INVARIANT under sweep fan-out (splitting "
                "members across chips divides both work and throughput "
                "equally), so the >=3x/chip target reduces to single-chip "
                "MFU x peak. Values = vs_baseline ceiling at given MFU."
            ),
            "ceilings": ceilings,
        },
    }

    # the load-bearing claims, asserted from the measurements:
    fanout = next(c for c in cases if c["name"] == "config2_sweep_fanout")
    assert fanout["collectives"]["wire_bytes_per_chip_per_step"] == 0, (
        "sweep fan-out must be collective-free; HLO says otherwise: "
        + json.dumps(fanout["collectives"]["summary"])
    )
    dictpar = next(c for c in cases if c["name"] == "config5_dictpar")
    assert dictpar["collectives"]["wire_bytes_per_chip_per_step"] > 0

    # comm-amortization crossover: gradient wire bytes are batch-invariant,
    # compute scales with batch, so batch*/shard where comm = 10% of compute
    # is (wire/bw) * 10 * mfu * peak / flops_per_row
    def crossover_batch(case, hw):
        wire = case["collectives"]["wire_bytes_per_chip_per_step"]
        rows = case["workload"]["batch_per_step"]
        flops_per_row_chip = case["matmul_flops_per_step_per_chip"] / rows
        bw = hw["links_per_axis"] * hw["ici_link_oneway_bytes_per_sec"]
        t_ici = wire / bw
        return int(
            t_ici * 10 * MEASURED_SINGLE_CHIP["mfu"] * hw["peak_bf16_flops"]
            / flops_per_row_chip / N_VIRTUAL_DEVICES
        ) * N_VIRTUAL_DEVICES

    report["conclusions"] = {
        "1_sweep_fanout_is_collective_free": (
            "Measured: the (model=16) program contains ZERO collective ops — "
            "sweep members are embarrassingly parallel, total throughput "
            "scales linearly with chips, acts/s/chip is invariant."
        ),
        "2_per_chip_target": (
            "Because fan-out leaves per-chip throughput invariant, the "
            ">=3x/chip target reduces to single-chip MFU x peak. v4 ceiling: "
            f"{ceilings[V4['name']]['mfu_1.0']}x at MFU 1.0 "
            f"({ceilings[V4['name']]['mfu_' + str(MEASURED_SINGLE_CHIP['mfu'])]}x "
            "at the measured 0.742) — >=3x vs the generous analytic A100 "
            "baseline is NOT reachable on v4-32; the binding constraint is "
            "chip peak FLOPs, not communication. On v5p-class chips the "
            f"ceiling is {ceilings[V5P['name']]['mfu_1.0']}x and >=3x needs "
            "MFU >= 0.85."
        ),
        "3_dp_needs_big_batches": {
            "statement": (
                "Gradient all-reduce wire bytes are batch-invariant, so the "
                "comm fraction falls as 1/batch. Measured wire + v4 ICI give "
                "these per-step batch sizes for <=10% comm overhead "
                "(no overlap assumed):"
            ),
            "batch_rows_for_10pct_comm": {
                c["name"]: crossover_batch(c, V4)
                for c in cases
                if c["collectives"]["wire_bytes_per_chip_per_step"] > 0
            },
        },
        "4_tied_grad_double_allreduce": (
            "FOUND AND FIXED (this round): plain autodiff gave the tied "
            "weights TWO grad-sized cotangent partials (encode-path + "
            "decode-path transposes) that GSPMD all-reduced separately — "
            "2x the gradient wire (hybrid case measured 2x16.8 MB). "
            "`FunctionalTiedSAE.bind_mesh` now swaps in a custom-VJP loss on "
            "data-parallel meshes whose tied backward is ONE contraction "
            "over a doubled batch axis (models/sae.py:_tied_pair_dp), so "
            "the partitioner emits a single grad-sized all-reduce operand. "
            "The wire numbers in `cases` are measured from the FIXED "
            "programs (hybrid 16.8 MB and pure-DP 126 MB, both half the "
            "r4-initial capture; dictpar 252 MB = 0.56x — its ~50 MB decode "
            "all-reduce is untouched); "
            "tests/test_parallel.py::test_dp_hlo_single_gradient_allreduce_"
            "operand pins the HLO to one operand."
        ),
        "5_caveats": (
            "HLO measured on the CPU SPMD partitioner (the TPU partitioner "
            "may schedule differently); ICI link constants assumed from "
            "public figures with a +-2x sensitivity band in `projections`; "
            "MFU transfer from the measured v5e 0.742 assumed."
        ),
    }

    out = REPO / f"SCALEOUT_{ROUND_TAG}.json"
    with open(out, "w") as f:
        json.dump(report, f, indent=1)
    print(f"Wrote {out}")
    for c in cases:
        s = c["collectives"]
        print(
            f"  {c['name']}: mesh {c['mesh']} -> "
            f"{s['wire_bytes_per_chip_per_step'] / 1e6:.2f} MB/chip/step wire, "
            f"ops={ {k: v['count'] for k, v in s['summary'].items()} }"
        )
    return report


if __name__ == "__main__":
    main()
