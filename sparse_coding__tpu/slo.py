"""CLI shim: ``python -m sparse_coding__tpu.slo <run_dir> --config slo.json``.

Evaluates declarative SLOs (availability, latency percentiles, queue
depth, goodput floor) over a run directory or live ``/metrics`` endpoints
(``--scrape URL...``), with error-budget consumption and fast/slow burn
rates; exits **1** past budget — the serving tier's CI gate and the
ROADMAP-3 autoscaler's sensor. Implementation:
`sparse_coding__tpu.telemetry.slo` (docs/observability.md §8).
"""

from sparse_coding__tpu.telemetry.slo import (
    evaluate_measured,
    evaluate_run_dir,
    evaluate_scrape,
    load_config,
    main,
    render_slo,
)

__all__ = [
    "evaluate_measured",
    "evaluate_run_dir",
    "evaluate_scrape",
    "load_config",
    "main",
    "render_slo",
]

if __name__ == "__main__":
    raise SystemExit(main())
