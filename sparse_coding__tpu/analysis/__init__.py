"""`sclint`: repo-native static analysis for TPU-correctness contracts.

Three of the nastiest bugs this repo has shipped were *statically
detectable contract violations*: the bf16 ``dtype.kind == 'f'`` check that
silently no-op'd int8 residency (numpy reports bfloat16 as kind ``'V'``),
the ``dequant`` span category that was missing from ``INNER_CATEGORIES``
and double-counted serving goodput, and the int8-nu Adam denominator
collapse. Each one survived review because the contract it broke lived in
another module. This package encodes those contracts as lint rules
(`rules`), walks the tree with a single-parse AST engine (`engine`), and
— for invariants a pure AST walk can't see — runs abstract contract checks
(`contracts`) built on ``jax.eval_shape`` and registry introspection, so no
TPU is needed.

CLI::

    python -m sparse_coding__tpu.analysis sparse_coding__tpu/ scripts/ bench.py

Exit codes: 0 = clean, 1 = findings, 3 = no Python files found. ``--json``
emits machine-readable findings, ``--baseline FILE`` grandfathers a
reviewed allowlist, ``--contracts`` adds the abstract checks. Rule catalog
and workflow: ``docs/STATIC_ANALYSIS.md``.

Suppression: a ``# sclint: allow(SC003) <reason>`` comment on the finding's
line, on the first line of its enclosing statement, or on a comment line
directly above it sanctions exactly that rule there — the idiom for the serve drainer's *deliberate* host syncs
(client response materialization), mirroring how `telemetry.audit`'s
``allowed_transfer()`` sanctions the train loop's once-per-chunk sync.
"""

from sparse_coding__tpu.analysis.findings import Finding
from sparse_coding__tpu.analysis.engine import (
    iter_python_files,
    lint_paths,
    load_baseline,
)

__all__ = ["Finding", "iter_python_files", "lint_paths", "load_baseline"]
