"""Chunk-store scrub: verify every chunk, quarantine failures, repair holes.

``python -m sparse_coding__tpu.data.scrub <store>`` walks one activation
chunk store (a folder of ``{i}.npy`` chunks + ``sc_chunk.<i>.json`` commit
manifests — `data.integrity`), verifies every chunk at the **digest** tier
by default (the depth hot-loop loads skip), and:

  - quarantines every failing chunk (moved into ``<store>/quarantine/``
    with a reason record — never deleted);
  - sweeps stale dot-prefixed staging temps a killed writer left behind;
  - reports holes: indices in ``[0, max]`` with no verifiable chunk
    (quarantined now or previously, torn away, or simply absent);
  - with ``--repair <config.json>``, re-harvests exactly the missing
    indices and re-verifies them;
  - prints a markdown summary and exits **1 while any unrepaired loss
    remains** — a CI admission gate over data directories, exactly like
    ``fleet.report``'s exit-1-on-lost-members.

Repair configs (JSON):

    {"kind": "synthetic", "generator": {...SparseMixDataset/
     RandomDatasetGenerator kwargs..., "class": "SparseMixDataset",
     "seed": 0}, "n_chunks": 8, "chunk_size_gb": 0.001,
     "activation_width": 64, "dtype": "float16"}

regenerates the quarantined indices through the same seeded generator
(`data.chunks.generate_synthetic_chunks(only_chunks=...)` — bit-exact,
the stream position advances deterministically past the surviving chunks).
LM-harvested stores are repaired through the harvest layer instead:
``make_activation_dataset(..., only_chunks=missing)`` (Python API) or a
``resume=True`` re-run, which re-harvests from the first unverifiable
chunk (docs/DATAPLANE.md §repair).

Fleet workers run the same verification as an **admission check** before
training an item whose payload names a ``dataset_folder``
(`fleet.worker`): corruption beyond the loss budget requeues the item
with an ``input_corrupt`` lineage entry instead of training on bad rows.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path
from typing import Any, Dict, List, Optional

from sparse_coding__tpu.data import integrity

__all__ = [
    "scrub_store",
    "repair_from_config",
    "render_scrub_markdown",
    "store_loss",
    "main",
]


def _store_indices(folder: Path) -> List[int]:
    """Every chunk index the store knows about: data files, commit
    manifests (a manifest whose data file vanished is still a loss to
    report), and the quarantine ledger."""
    idx = set()
    for p in folder.iterdir():
        if p.suffix == ".npy" and p.stem.isdigit():
            idx.add(int(p.stem))
        elif p.name.startswith("sc_chunk.") and p.suffix == ".json":
            mid = p.name[len("sc_chunk."):-len(".json")]
            if mid.isdigit():
                idx.add(int(mid))
    idx.update(integrity.quarantined_indices(folder))
    return sorted(idx)


def _expected_top(folder: Path, idx: List[int]) -> int:
    """Highest chunk index the store SHOULD hold. The max index present on
    disk alone is blind to wholesale tail loss (a partial copy that drops
    chunks 6-9 with their manifests looks 'whole' up to 5), so the harvest
    cursor — which records how many chunks were committed — raises the
    floor when present."""
    from sparse_coding__tpu.data.activations import read_harvest_cursor

    top = max(idx) if idx else -1
    cursor = read_harvest_cursor(folder)
    if cursor is not None and isinstance(cursor.get("chunk"), int):
        top = max(top, int(cursor["chunk"]) - 1)
    return top


def _sweep_stale_temps(folder: Path) -> List[str]:
    """Dot-prefixed staging temps (` .{name}.tmp{pid}`) from killed writers:
    swept when their writer is dead, left alone while it might be mid-dump
    (same discipline as `train.checkpoint.save_learned_dicts`)."""
    import os

    swept = []
    for stale in folder.glob(".*.tmp*"):
        try:
            os.kill(int(stale.name.rsplit("tmp", 1)[-1]), 0)
        except (ValueError, ProcessLookupError):
            stale.unlink(missing_ok=True)
            swept.append(stale.name)
        except PermissionError:
            pass  # alive under another uid: leave it
    return swept


def scrub_store(
    folder, depth: str = "digest", quarantine: bool = True,
    sweep_temps: bool = True,
) -> Dict[str, Any]:
    """Verify every chunk in `folder`; quarantine failures. Returns a
    summary dict (see `render_scrub_markdown` for the fields).
    ``quarantine=False, sweep_temps=False`` makes the pass fully
    non-mutating (the admission-check mode, `store_loss`)."""
    folder = Path(folder)
    if not folder.is_dir():
        raise FileNotFoundError(f"chunk store {folder} does not exist")
    depth = integrity.verify_depth(depth)
    pre_quarantined = integrity.quarantined_indices(folder)
    swept = _sweep_stale_temps(folder) if sweep_temps else []
    verified: List[int] = []
    failed: List[Dict[str, Any]] = []
    for i in _store_indices(folder):
        if i in pre_quarantined and not (folder / f"{i}.npy").exists():
            continue  # already quarantined in a previous pass
        ok, reason = integrity.verify_chunk(folder, i, depth=depth)
        if ok:
            verified.append(i)
            continue
        if quarantine:
            integrity.quarantine_chunk(folder, i, reason)
        failed.append({"chunk": i, "reason": reason})
    all_idx = sorted(
        set(verified) | {f["chunk"] for f in failed} | set(pre_quarantined)
    )
    top = _expected_top(folder, all_idx)
    missing = sorted(set(range(top + 1)) - set(verified))
    return {
        "store": str(folder),
        "depth": depth,
        "total": top + 1,
        "verified": verified,
        "failed": failed,
        "pre_quarantined": pre_quarantined,
        "missing": missing,
        "swept_temps": swept,
        "repaired": [],
    }


def store_loss(folder, depth: Optional[str] = None) -> Dict[str, Any]:
    """Non-mutating loss estimate for admission checks: `scrub_store` with
    every mutation off, reduced to ``{loss_frac, bad, total}`` where
    ``bad`` covers failing, missing, and already-quarantined indices —
    ONE verification sweep, so the fleet admission verdict can never
    diverge from the scrub CLI's."""
    summary = scrub_store(
        folder, depth=depth or "digest", quarantine=False, sweep_temps=False
    )
    total = summary["total"]
    return {
        "loss_frac": (len(summary["missing"]) / total) if total else 0.0,
        "bad": summary["missing"],
        "total": total,
    }


def repair_from_config(folder, indices, config: Dict[str, Any]) -> List[int]:
    """Re-generate exactly `indices` of the store from a repair config
    (module docstring). Returns the indices re-verified OK afterwards."""
    if not indices:
        return []
    folder = Path(folder)
    kind = config.get("kind")
    if kind == "synthetic":
        import jax

        from sparse_coding__tpu.data import synthetic as syn
        from sparse_coding__tpu.data.chunks import generate_synthetic_chunks

        gen_cfg = dict(config.get("generator") or {})
        cls = getattr(syn, gen_cfg.pop("class", "SparseMixDataset"))
        seed = int(gen_cfg.pop("seed", 0))
        generator = cls(**gen_cfg, key=jax.random.PRNGKey(seed))
        import numpy as np

        dtype = config.get("dtype", "float16")
        generate_synthetic_chunks(
            generator, folder,
            n_chunks=int(config["n_chunks"]),
            chunk_size_gb=float(config.get("chunk_size_gb", 2.0)),
            activation_width=config.get("activation_width"),
            dtype=dtype if str(dtype) == "int4" else np.dtype(dtype),
            only_chunks=indices,
        )
    elif kind == "harvest":
        from sparse_coding__tpu.data.activations import setup_data

        # the harvest layer re-runs with resume semantics: everything from
        # the first unverifiable chunk is re-captured (deterministic, so the
        # surviving suffix is rewritten bit-identically)
        setup_data(**dict(config.get("setup") or {}), resume=True)
    else:
        raise ValueError(
            f"unknown repair config kind {kind!r} (synthetic | harvest)"
        )
    repaired = []
    for i in indices:
        ok, _ = integrity.verify_chunk(folder, i, depth="digest")
        if ok:
            repaired.append(i)
    return repaired


def render_scrub_markdown(summary: Dict[str, Any]) -> str:
    unrepaired = sorted(set(summary["missing"]) - set(summary.get("repaired", [])))
    lines = [f"# Chunk-store scrub — `{summary['store']}`", ""]
    lines.append(
        f"Verified **{len(summary['verified'])}** chunk(s) at the "
        f"`{summary['depth']}` tier; "
        f"**{len(summary['failed'])} quarantined** this pass, "
        f"{len(summary['pre_quarantined'])} already in quarantine, "
        f"{len(summary.get('repaired', []))} repaired."
    )
    lines.append("")
    if summary["failed"]:
        lines.append("| chunk | verdict |")
        lines.append("|---:|---|")
        for f in summary["failed"]:
            lines.append(f"| {f['chunk']} | {f['reason']} |")
        lines.append("")
    if summary.get("swept_temps"):
        lines.append(
            f"- swept {len(summary['swept_temps'])} stale staging temp(s) "
            "from dead writers"
        )
        lines.append("")
    if unrepaired:
        lines.append(
            f"⚠ **UNREPAIRED LOSS**: chunk(s) {unrepaired} have no "
            "verifiable data. Re-harvest them (`--repair <config.json>`, or "
            "`make_activation_dataset(..., only_chunks=...)` /"
            " `resume=True` — docs/DATAPLANE.md), or train in degraded mode "
            "within `SC_CHUNK_LOSS_BUDGET`."
        )
    else:
        lines.append("All chunk indices verify — store is whole. ✓")
    lines.append("")
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m sparse_coding__tpu.data.scrub",
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument("store", help="chunk store folder ({i}.npy + sc_chunk.<i>.json)")
    ap.add_argument("--depth", default="digest",
                    choices=("digest", "size", "off"),
                    help="verification tier (default digest — the scrub "
                    "exists to catch what the hot loop's size tier cannot)")
    ap.add_argument("--no-quarantine", action="store_true",
                    help="report failures without moving files")
    ap.add_argument("--repair", default=None, metavar="CONFIG.json",
                    help="re-harvest missing/quarantined indices from a "
                    "repair config (see module docstring)")
    ap.add_argument("--out", default=None, help="also write the markdown here")
    args = ap.parse_args(argv)

    summary = scrub_store(
        args.store, depth=args.depth, quarantine=not args.no_quarantine
    )
    if args.repair and summary["missing"]:
        with open(args.repair) as f:
            config = json.load(f)
        summary["repaired"] = repair_from_config(
            args.store, summary["missing"], config
        )
    md = render_scrub_markdown(summary)
    print(md)
    if args.out:
        Path(args.out).write_text(md + "\n")
        print(f"[written to {args.out}]")
    unrepaired = set(summary["missing"]) - set(summary.get("repaired", []))
    return 1 if unrepaired else 0


if __name__ == "__main__":
    raise SystemExit(main())
