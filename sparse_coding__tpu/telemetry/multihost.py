"""Pod-scale telemetry: per-process event logs that merge into one story.

PR 2/3 built single-process observability (`events.RunTelemetry`,
`profiling`, the report CLI). On a multi-host run every host is its own
Python process with its own clock and its own disk writes, so this module
adds the pod layer (docs/observability.md §5):

  - **Per-process log layout.** `RunTelemetry` consults `process_info()` at
    construction: in a multi-host run (`jax.process_count() > 1`) the event
    file becomes ``events.p<i>.jsonl`` and every record is tagged
    ``process_index`` — so merged timelines, anomalies, and compile events
    all know their originating host. Single-host runs keep today's layout
    (``events.jsonl``, untagged) bit-for-bit.
  - **Clock alignment** (`estimate_clock_offset` / `clock_state`). Host
    wall clocks disagree; merged timelines need a common axis. At
    `parallel.distributed.initialize_distributed()` (and periodically at
    flush boundaries — see `heartbeat`) every host publishes its
    ``time.time()`` and records ``offset = local_receive −
    coordinator_send`` with the local round-trip as the uncertainty. A
    cheap estimate — good to exchange-latency resolution, which is
    exactly the resolution merged flush-boundary events need.
  - **Heartbeats + straggler skew** (`heartbeat`). At each flush boundary
    the drivers call `heartbeat(telemetry, step=..., window_seconds=...)`:
    one small all-host exchange of the per-host window wall time yields
    the flush-window skew (max−min across hosts), emitted as
    ``skew.flush.*`` gauges and a ``heartbeat`` event per host. Exchanges
    run ONLY at flush boundaries (never in the hot loop) and only when
    ``process_count > 1``; the SPMD drivers hit boundaries in lockstep, so
    the exchange rounds always match up.
  - **Desync detection** (`check_desync`). A pod where hosts disagree on
    code version, jax version, backend, or run config is silently broken
    long before it crashes. At run start the drivers digest a comparable
    fingerprint subset + the run config, exchange the digests, and any
    mismatch against the coordinator becomes a hard ``desync`` anomaly
    event (plus `AnomalyAbort` under ``action="abort"``). The merged
    report diffs the actual fingerprint fields offline.

**Transport.** All cross-host exchanges ride jax's distributed
coordination service (the KV store every `jax.distributed.initialize`
process already holds) — pure host-side string puts/gets, NO device
computation and no XLA collective. That keeps telemetry off the ICI/DCN
data path entirely, makes "zero extra device syncs" literal, and works on
backends (like the simulated-pod CPU+gloo harness) where cross-process
XLA computations are unavailable. Exchange rounds are matched by a
per-tag call counter, so every host must reach the same call sites in the
same order — true for the SPMD drivers, whose flush boundaries are
already pod-wide sync points.

Offline halves (`chunk_skew_windows`, `fingerprint_diff`) are pure
functions over parsed event records — `telemetry.report` and
`telemetry.monitor` share them, and they need no jax at all.
"""

from __future__ import annotations

import hashlib
import json
import re
import time
import warnings
from typing import Any, Dict, List, Optional, Sequence, Tuple

from sparse_coding__tpu.utils import flags

__all__ = [
    "process_info",
    "per_process_file_name",
    "estimate_clock_offset",
    "clock_state",
    "heartbeat",
    "check_desync",
    "comparable_fingerprint",
    "chunk_skew_windows",
    "fingerprint_diff",
    "format_bytes",
    "PROC_FILE_RE",
]

# the per-process log-name suffix (`per_process_file_name`); report and
# monitor share this to recover a record's host from its filename when the
# record itself is untagged (older telemetry versions)
PROC_FILE_RE = re.compile(r"\.p(\d+)\.jsonl$")


def format_bytes(v) -> str:
    """Human bytes for report/monitor tables; '-' for None/non-numeric."""
    try:
        v = float(v)
    except (TypeError, ValueError):
        return "-"
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(v) < 1024 or unit == "TiB":
            return f"{v:.2f} {unit}" if unit != "B" else f"{int(v)} B"
        v /= 1024
    return "-"  # pragma: no cover

# fingerprint keys that must agree across a pod; everything else
# (process_index, compile-cache entry counts, clock fields) is legitimately
# per-host
COMPARABLE_FINGERPRINT_KEYS = (
    "python", "jax", "jaxlib", "backend", "device_kind", "device_count",
    "process_count", "git_sha", "mesh",
)

# re-estimate the clock offset every Nth heartbeat (count-based, NOT
# time-based: hosts must decide identically or the exchange rounds skew)
CLOCK_RESYNC_EVERY_ENV = flags.SC_CLOCK_RESYNC_EVERY.name
_CLOCK_RESYNC_DEFAULT = 16

# how long one host waits for the others' KV payloads before giving up on
# that exchange round (a missed heartbeat, not a crash)
TIMEOUT_MS_ENV = flags.SC_MH_TIMEOUT_MS.name
_TIMEOUT_MS_DEFAULT = 60_000

# module state: the most recent clock-offset estimate for this process
_CLOCK: Dict[str, float] = {}

# per-tag exchange round counters (matched across hosts by SPMD lockstep)
_ROUNDS: Dict[str, int] = {}


def process_info() -> Tuple[int, int]:
    """(process_index, process_count), best-effort: (0, 1) whenever jax is
    unavailable or the backend refuses — telemetry must never fail a run."""
    try:
        import jax

        return int(jax.process_index()), int(jax.process_count())
    except Exception:
        return 0, 1


def per_process_file_name(base: str, index: int, count: int) -> str:
    """``events.jsonl`` -> ``events.p<i>.jsonl`` in a pod; unchanged
    single-host (the acceptance contract: single-host layout is stable)."""
    if count <= 1:
        return base
    stem, dot, ext = base.rpartition(".")
    if not dot:
        return f"{base}.p{index}"
    return f"{stem}.p{index}.{ext}"


# -- KV-store exchange primitive ----------------------------------------------

def _coord_client():
    """jax's distributed-coordination client (present on every process after
    `jax.distributed.initialize`), or None outside a pod. Private jax
    surface, so access is defensive — telemetry degrades, runs never
    fail."""
    try:
        from jax._src import distributed as _dist

        return _dist.global_state.client
    except Exception:
        return None


def _timeout_ms() -> int:
    try:
        return flags.SC_MH_TIMEOUT_MS.get()
    except ValueError:
        return _TIMEOUT_MS_DEFAULT


def _kv_allgather(tag: str, payload: str) -> Optional[List[str]]:
    """All-host exchange of one small string per host, through the
    coordination-service KV store: host i sets ``sc_mh/<tag>/<round>/<i>``
    then blocking-gets every host's key. Pure host-side I/O — no device,
    no XLA. Rounds are numbered per tag so repeated exchanges at the same
    call site pair up across hosts (requires SPMD-lockstep call order —
    the flush-boundary contract). Returns the per-process payload list, or
    None single-host / when the exchange is unavailable or times out."""
    idx, count = process_info()
    if count <= 1:
        return None
    client = _coord_client()
    if client is None:
        return None
    n = _ROUNDS.get(tag, 0)
    _ROUNDS[tag] = n + 1
    timeout = _timeout_ms()
    try:
        client.key_value_set(f"sc_mh/{tag}/{n}/{idx}", payload)
        return [
            client.blocking_key_value_get(f"sc_mh/{tag}/{n}/{p}", timeout)
            for p in range(count)
        ]
    except Exception:
        return None


# -- clock alignment ----------------------------------------------------------

def estimate_clock_offset() -> Optional[Dict[str, float]]:
    """One clock probe; returns (and stashes in `clock_state`)

        {"offset_seconds":      local clock minus coordinator clock,
         "uncertainty_seconds": how long this host blocked for the value,
         "measured_at":         local time.time() of the measurement}

    Asymmetric by construction: the coordinator publishes its
    ``time.time()`` to the KV store and is pinned to offset **0.0** (it IS
    the reference clock); every other host times the blocking fetch of that
    key and records ``offset = fetch_return − coordinator_send``. A host
    that arrives *before* the coordinator blocks until the key lands, so
    its estimate is tight to KV transit; a host arriving *after* absorbs
    the arrival skew into the offset — ``uncertainty_seconds`` (the wall
    spent blocked) disambiguates: a long block means a tight estimate.
    Good to call-site-skew resolution, which is all a merged
    flush-boundary timeline needs. None (and no state update) single-host
    or on any failure. Matched probe: call it only where every process
    calls it too (init, count-based heartbeat resync) — never in the hot
    loop.
    """
    idx, count = process_info()
    if count <= 1:
        return None
    client = _coord_client()
    if client is None:
        return None
    n = _ROUNDS.get("clock", 0)
    _ROUNDS["clock"] = n + 1
    key = f"sc_mh/clock/{n}/0"
    try:
        if idx == 0:
            now = time.time()
            client.key_value_set(key, repr(now))
            est = {
                "offset_seconds": 0.0,
                "uncertainty_seconds": 0.0,
                "measured_at": now,
            }
        else:
            t_before = time.time()
            coord_sent = float(client.blocking_key_value_get(key, _timeout_ms()))
            t_after = time.time()
            est = {
                "offset_seconds": round(t_after - coord_sent, 6),
                "uncertainty_seconds": round(t_after - t_before, 6),
                "measured_at": t_after,
            }
    except Exception:
        return None
    _CLOCK.clear()
    _CLOCK.update(est)
    return est


def clock_state() -> Optional[Dict[str, float]]:
    """The most recent `estimate_clock_offset` result for this process, or
    None when never measured (single-host runs)."""
    return dict(_CLOCK) if _CLOCK else None


# -- heartbeats + straggler skew ----------------------------------------------

def heartbeat(
    telemetry,
    step: Optional[int] = None,
    window_seconds: Optional[float] = None,
) -> Optional[Dict[str, Any]]:
    """Flush-boundary host heartbeat. No-op single-host (layout stability).

    In a pod: exchanges the per-host flush-window wall time (one tiny
    KV-store round — the boundary is already a sync point for SPMD
    drivers, and no device is touched), sets the straggler gauges

        skew.flush.max_seconds / min_seconds / spread_seconds

    (identical on every host, post-exchange), and emits a ``heartbeat``
    event carrying the local cumulative step counter, the per-host window
    times, and the current clock-offset estimate — the monitor's liveness
    and live-throughput signal. Every `SC_CLOCK_RESYNC_EVERY` (default 16)
    calls the clock offset is re-estimated (count-based so all hosts
    re-enter the exchange together).

    ``window_seconds`` is the host-local wall time of the window just
    closed (e.g. `chunk_end`'s seconds); when omitted it is measured as
    time since this telemetry's previous heartbeat. Returns the event
    record, or None single-host / on exchange failure.
    """
    idx, count = process_info()
    if count <= 1 or telemetry is None:
        return None
    now = time.time()
    last = getattr(telemetry, "_mh_last_heartbeat_t", None)
    if window_seconds is None:
        window_seconds = (now - last) if last is not None else 0.0
    telemetry._mh_last_heartbeat_t = now
    n_beats = getattr(telemetry, "_mh_heartbeats", 0) + 1
    telemetry._mh_heartbeats = n_beats

    resync_every = _CLOCK_RESYNC_DEFAULT
    try:
        override = flags.SC_CLOCK_RESYNC_EVERY.get()
        if override is not None:
            resync_every = override
    except ValueError:
        pass
    if resync_every > 0 and n_beats % resync_every == 0:
        estimate_clock_offset()

    raw = _kv_allgather("heartbeat", repr(float(window_seconds)))
    if raw is None:
        return None
    try:
        windows = [float(v) for v in raw]
    except ValueError:
        return None
    w_max, w_min = max(windows), min(windows)
    telemetry.gauge_set("skew.flush.max_seconds", round(w_max, 4))
    telemetry.gauge_set("skew.flush.min_seconds", round(w_min, 4))
    telemetry.gauge_set("skew.flush.spread_seconds", round(w_max - w_min, 4))
    telemetry.counter_inc("heartbeats")
    clock = clock_state() or {}
    return telemetry.event(
        "heartbeat",
        step=int(step) if step is not None else None,
        steps=int(telemetry.counters.get("train.steps", 0)),
        window_seconds=round(float(window_seconds), 4),
        window_seconds_by_process=[round(float(w), 4) for w in windows],
        skew_seconds=round(w_max - w_min, 4),
        clock_offset_seconds=clock.get("offset_seconds"),
        clock_uncertainty_seconds=clock.get("uncertainty_seconds"),
    )


# -- desync detection ---------------------------------------------------------

def comparable_fingerprint(config: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    """The fingerprint subset every pod host must agree on, plus the run
    config — the digest input for `check_desync` and the diff basis for the
    merged report."""
    from sparse_coding__tpu.telemetry.events import run_fingerprint

    fp = run_fingerprint()
    out = {k: fp[k] for k in COMPARABLE_FINGERPRINT_KEYS if k in fp}
    if config is not None:
        out["config"] = config
    return out


def _digest(payload: Dict[str, Any]) -> str:
    return hashlib.sha256(
        json.dumps(payload, sort_keys=True, default=str).encode()
    ).hexdigest()[:16]


def check_desync(
    telemetry=None,
    config: Optional[Dict[str, Any]] = None,
    action: str = "warn",
) -> Optional[List[int]]:
    """Cross-host config/environment agreement check (run-start boundary).

    Digests `comparable_fingerprint(config)`, exchanges the digests through
    the KV store, and compares every host against the coordinator (process
    0). On mismatch: a hard ``desync`` anomaly event (tagged with this
    process via the record-level ``process_index``), a `RuntimeWarning`,
    and — under ``action="abort"`` — an `AnomalyAbort` so the driver can
    stop before wasting pod hours on a split-brained run.

    Returns the sorted list of mismatching process indices ([] = healthy),
    or None single-host / when the exchange is unavailable. Matched
    exchange: call at identical points on every host (the drivers call it
    right after `run_start`).
    """
    if action not in ("warn", "abort"):
        raise ValueError(f"unknown desync action {action!r}")
    idx, count = process_info()
    if count <= 1:
        return None
    local = _digest(comparable_fingerprint(config))
    digests = _kv_allgather("desync", local)
    if digests is None:
        return None
    reference = digests[0]
    mismatched = sorted(p for p in range(count) if digests[p] != reference)
    if not mismatched:
        return []
    desc = (
        f"desync: processes {mismatched} disagree with the coordinator's "
        f"config/environment fingerprint (local p{idx} "
        f"{'matches' if idx not in mismatched else 'MISMATCHES'})"
    )
    if telemetry is not None:
        telemetry.anomaly(
            "desync",
            processes=mismatched,
            local_digest=local,
            reference_digest=reference,
            local_match=idx not in mismatched,
            action=action,
        )
    warnings.warn(desc, RuntimeWarning)
    if action == "abort":
        from sparse_coding__tpu.telemetry.anomaly import AnomalyAbort

        raise AnomalyAbort(desc)
    return mismatched


# -- offline halves (no jax): shared by report + monitor ----------------------

def chunk_skew_windows(events: Sequence[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """Per-window cross-host chunk-time skew from merged `chunk_end` events.

    Windows are keyed by ``(epoch, chunk, position)`` (absent fields are
    None — the drivers' chunk ids line up across hosts because the chunk
    schedule is seed-derived and identical pod-wide). Only windows covered
    by ≥2 distinct processes produce a row::

        {"key": (...), "seconds": {proc: s, ...}, "max": s, "min": s,
         "spread": s}

    sorted in first-seen order. Re-emitted windows (restarts) keep the last
    observation per process.
    """
    windows: Dict[tuple, Dict[int, float]] = {}
    order: List[tuple] = []
    for e in events:
        # seconds=None = chunk_end without a matching chunk_start (resumed
        # generation's torn window): no usable duration for skew either
        if e.get("event") != "chunk_end" or not isinstance(
            e.get("seconds"), (int, float)
        ):
            continue
        key = (e.get("epoch"), e.get("chunk"), e.get("position"))
        proc = int(e.get("process_index", 0))
        if key not in windows:
            windows[key] = {}
            order.append(key)
        windows[key][proc] = float(e["seconds"])
    out = []
    for key in order:
        secs = windows[key]
        if len(secs) < 2:
            continue
        vals = list(secs.values())
        out.append(
            {
                "key": key,
                "seconds": secs,
                "max": max(vals),
                "min": min(vals),
                "spread": max(vals) - min(vals),
            }
        )
    return out


def fingerprint_diff(
    run_starts: Sequence[Dict[str, Any]],
) -> Dict[str, Dict[int, Any]]:
    """Offline desync attribution: given merged ``run_start`` events, return
    ``{field: {process: value}}`` for every comparable fingerprint field (or
    config) on which the hosts disagree — the human-readable counterpart of
    `check_desync`'s digest mismatch. Empty dict = all hosts agree."""
    per_proc: Dict[int, Dict[str, Any]] = {}
    for s in run_starts:
        proc = int(s.get("process_index", 0))
        fp = s.get("fingerprint") or {}
        row = {k: fp.get(k) for k in COMPARABLE_FINGERPRINT_KEYS}
        row["config"] = s.get("config")
        per_proc[proc] = row
    if len(per_proc) < 2:
        return {}
    diff: Dict[str, Dict[int, Any]] = {}
    fields = set()
    for row in per_proc.values():
        fields.update(row)
    for f in sorted(fields):
        vals = {p: per_proc[p].get(f) for p in sorted(per_proc)}
        canon = {p: json.dumps(v, sort_keys=True, default=str) for p, v in vals.items()}
        if len(set(canon.values())) > 1:
            diff[f] = vals
    return diff
