"""Abstract contract checks — invariants a pure AST walk cannot see.

These run real repo code against *abstract* values (``jax.eval_shape`` plus
registry introspection), so they need no TPU, no devices, and allocate no
arrays. The flagship check is partition-rule coverage: every leaf of the
ensemble + optimizer state trees must be classified by an explicit
`parallel.mesh.infer_state_specs` rule, because an unclassified leaf
defaults to replication and the first symptom is an OOM (or a silent 4x
memory bill) at sweep scale, not a test failure.

Run via ``python -m sparse_coding__tpu.analysis --contracts``.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Tuple

__all__ = ["ContractResult", "CONTRACTS", "run_contracts"]


@dataclasses.dataclass
class ContractResult:
    name: str
    ok: bool
    summary: str
    details: List[str] = dataclasses.field(default_factory=list)

    def render(self) -> str:
        mark = "ok" if self.ok else "FAIL"
        lines = [f"[{mark}] {self.name}: {self.summary}"]
        lines += [f"       {d}" for d in self.details]
        return "\n".join(lines)


CONTRACTS: Dict[str, Callable[[], ContractResult]] = {}


def contract(name: str):
    def deco(fn):
        CONTRACTS[name] = fn
        return fn

    return deco


# -- partition-rule coverage --------------------------------------------------

class _FakeMesh:
    """Duck-typed stand-in for `jax.sharding.Mesh`: `infer_state_specs` only
    reads ``mesh.shape``, so the contract can run with zero devices."""

    def __init__(self, shape: Dict[str, int]):
        self.shape = shape


def _abstract_ensemble_state(n_models: int, activation_size: int,
                             n_dict_components: int):
    """The real state tree — params, buffers, adam opt_state, step — built
    abstractly: `jax.eval_shape` traces the exact constructors `Ensemble`
    uses (sig.init → stack_pytrees → vmap(tx.init)) without allocating."""
    import jax
    import jax.numpy as jnp

    from sparse_coding__tpu import ensemble as ens
    from sparse_coding__tpu.models.sae import FunctionalTiedSAE

    tx = ens.optim_str_to_func("adam")(learning_rate=1e-3)

    def build(key):
        keys = jax.random.split(key, n_models)
        models = [
            FunctionalTiedSAE.init(
                k, activation_size, n_dict_components, l1_alpha=1e-3
            )
            for k in keys
        ]
        params_list, buffers_list = zip(*models)
        params = ens.stack_pytrees(list(params_list))
        buffers = ens.stack_pytrees(list(buffers_list))
        opt_state = jax.vmap(tx.init)(params)
        return ens.EnsembleState(
            params=params,
            buffers=buffers,
            opt_state=opt_state,
            step=jnp.zeros((), jnp.int32),
        )

    return jax.eval_shape(build, jax.random.PRNGKey(0))


def _leaf_paths(tree) -> List[Tuple[str, Any]]:
    import jax

    out = []
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        out.append((jax.tree_util.keystr(path), leaf))
    return out


@contract("partition-coverage")
def partition_coverage(
    n_models: int = 4, activation_size: int = 64, n_dict_components: int = 128
) -> ContractResult:
    """Every leaf of the ensemble + optimizer state trees must be matched by
    an explicit `infer_state_specs` rule: stacked leaves (leading dim ==
    n_models) get the model axis (dict axis too when their dim 1 divides the
    dict mesh size), everything else is *deliberately* replicated (scalars,
    step counters). A stacked leaf that comes back fully replicated means a
    new state field slipped past the spec table — the silent-OOM class."""
    from sparse_coding__tpu.parallel import mesh as pmesh

    state = _abstract_ensemble_state(n_models, activation_size, n_dict_components)
    fake = _FakeMesh({pmesh.MODEL_AXIS: n_models, pmesh.DICT_AXIS: 4})
    specs = pmesh.infer_state_specs(state, n_models, fake, shard_dict=True)
    dict_size = fake.shape[pmesh.DICT_AXIS]

    leaves = _leaf_paths(state)
    spec_leaves = dict(_leaf_paths_specs(specs))
    uncovered: List[str] = []
    covered = 0
    for path, leaf in leaves:
        shape = tuple(leaf.shape)
        spec = spec_leaves.get(path)
        axes = tuple(spec) if spec is not None else None
        stacked = len(shape) >= 1 and shape[0] == n_models
        if axes is None:
            uncovered.append(f"{path} {shape}: no spec leaf produced")
        elif stacked:
            if not axes or axes[0] != pmesh.MODEL_AXIS:
                uncovered.append(
                    f"{path} {shape}: stacked leaf not placed on the model axis "
                    f"(spec {axes}) — replicated n_models times"
                )
            elif (
                pmesh.DICT_AXIS in axes
                and (len(shape) < 2 or shape[1] % dict_size != 0)
            ):
                uncovered.append(
                    f"{path} {shape}: dict-axis spec with indivisible dim 1"
                )
            else:
                covered += 1
        else:
            if any(a is not None for a in axes):
                uncovered.append(
                    f"{path} {shape}: unstacked leaf given sharded spec {axes}"
                )
            else:
                covered += 1

    total = len(leaves)
    ok = not uncovered
    return ContractResult(
        name="partition-coverage",
        ok=ok,
        summary=(
            f"{covered}/{total} state leaves classified by an explicit "
            f"partition rule (ensemble params+buffers+adam moments, "
            f"n_models={n_models})"
        ),
        details=uncovered,
    )


def _leaf_paths_specs(tree) -> List[Tuple[str, Any]]:
    """Like `_leaf_paths`, but PartitionSpec leaves: a P() is a pytree node
    with no children under default flattening, so flatten with
    ``is_leaf``."""
    import jax
    from jax.sharding import PartitionSpec

    out = []
    flat = jax.tree_util.tree_flatten_with_path(
        tree, is_leaf=lambda x: isinstance(x, PartitionSpec)
    )[0]
    for path, leaf in flat:
        out.append((jax.tree_util.keystr(path), leaf))
    return out


# -- span-table invariants ----------------------------------------------------

@contract("span-tables")
def span_tables() -> ContractResult:
    """Structural invariants of the telemetry category registry: the three
    tables are disjoint (a category in two tables is double-counted by
    construction), and every nestable (INNER) category is itself emittable
    — an INNER entry nobody can emit is a dead suppression rule."""
    from sparse_coding__tpu.analysis.context import RepoContext

    t = RepoContext().span_tables
    good, bad, derived, inner = (
        set(t["GOODPUT_CATEGORIES"]), set(t["BADPUT_CATEGORIES"]),
        set(t["DERIVED_CATEGORIES"]), set(t["INNER_CATEGORIES"]),
    )
    problems: List[str] = []
    for a, b, name in (
        (good, bad, "GOODPUT∩BADPUT"),
        (good, derived, "GOODPUT∩DERIVED"),
        (bad, derived, "BADPUT∩DERIVED"),
    ):
        if a & b:
            problems.append(f"{name} = {sorted(a & b)}")
    dead_inner = inner - (good | bad)
    if dead_inner:
        problems.append(f"INNER categories nobody can emit: {sorted(dead_inner)}")
    for table_name in ("GOODPUT_CATEGORIES", "BADPUT_CATEGORIES"):
        seq = t[table_name]
        if len(seq) != len(set(seq)):
            problems.append(f"duplicates inside {table_name}")
    return ContractResult(
        name="span-tables",
        ok=not problems,
        summary=(
            f"{len(good)} goodput / {len(bad)} badput / {len(derived)} "
            f"derived categories, {len(inner)} nestable"
        ),
        details=problems,
    )


# -- flags/docs sync ----------------------------------------------------------

@contract("flags-docs")
def flags_docs() -> ContractResult:
    """The flag table in docs/observability.md is generated from
    `utils.flags.FLAGS`; this fails when the registry changed but
    ``python -m sparse_coding__tpu.utils.flags --update-docs`` wasn't
    re-run."""
    from sparse_coding__tpu.utils import flags

    ok = flags.check_docs()
    return ContractResult(
        name="flags-docs",
        ok=ok,
        summary=(
            f"docs flag table in sync ({len(flags.FLAGS)} flags)" if ok
            else "docs/observability.md flag table is stale — run "
                 "python -m sparse_coding__tpu.utils.flags --update-docs"
        ),
    )


def run_contracts() -> List[ContractResult]:
    return [fn() for fn in CONTRACTS.values()]
