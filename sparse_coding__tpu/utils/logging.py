"""Buffered metric logging: wandb when available, JSONL fallback otherwise.

Replaces the reference's logging pattern — per-batch `wandb.log` of `.item()`'d
scalars (`big_sweep.py:204-228`), which forces a host sync every step and would
stall a TPU pipeline (SURVEY.md §7 "hard parts"). Here scalars stay on device
in a ring buffer of pytrees; `flush()` does ONE `jax.device_get` for the whole
window and emits per-model series.

wandb is not part of this image's environment; when importable (and
`use_wandb=True`) it is used, otherwise metrics append to a JSONL file — the
same record schema either way, so analysis tooling reads both.
"""

from __future__ import annotations

import json
import time
from pathlib import Path
from typing import Any, Callable, Dict, List, Optional

import jax

from sparse_coding__tpu.telemetry.audit import allowed_transfer


def format_hyperparam_val(val) -> str:
    """(reference `format_hyperparam_val`, `big_sweep.py:76-80`)"""
    return f"{val:.2E}".replace("+", "") if isinstance(val, float) else str(val)


def make_hyperparam_name(hyperparam_values: Dict[str, Any]) -> str:
    """Stable per-model series name, e.g. ``l1_alpha_1.00E-03``
    (reference `make_hyperparam_name`, `big_sweep.py:83-84`)."""
    return "_".join(
        f"{k}_{format_hyperparam_val(hyperparam_values[k])}"
        for k in sorted(hyperparam_values)
    )


class MetricLogger:
    """Buffered, host-sync-free metric logger.

    `log(step, tree)` stores device scalars without transfer; `flush()` pulls
    everything in one transfer and writes records
    ``{"step": int, "series": str, "metric": str, "value": float}``.

    ``on_flush(steps, trees)`` (optional) receives each flush window's
    host-side payload AFTER it is written — `telemetry.anomaly.AnomalyGuard.
    observe` plugs in here, so anomaly detection costs zero extra device
    syncs and runs exactly at the flush boundary. Exceptions it raises
    (e.g. `AnomalyAbort`) propagate to the training loop with the window
    already safely on disk.
    """

    def __init__(
        self,
        out_dir: Optional[str] = None,
        run_name: str = "run",
        use_wandb: bool = False,
        wandb_project: str = "sparse_coding__tpu",
        model_names: Optional[List[str]] = None,
        on_flush: Optional[Callable[[List[int], List[Dict[str, Any]]], None]] = None,
    ):
        self.model_names = model_names
        self.on_flush = on_flush
        self._buffer: List = []
        self._wandb = None
        self._jsonl = None
        if use_wandb:
            try:
                import wandb

                self._wandb = wandb.init(project=wandb_project, name=run_name)
            except Exception:
                self._wandb = None
        self._out_dir = Path(out_dir) if out_dir is not None else None
        if self._wandb is None and out_dir is not None:
            path = Path(out_dir)
            path.mkdir(parents=True, exist_ok=True)
            # multi-host runs write per-process files on the shared run dir
            # (interleaved appends from N hosts tear JSONL lines); the
            # `_p<i>_metrics.jsonl` form still matches the report's
            # `*_metrics.jsonl` glob. Single-host name unchanged.
            from sparse_coding__tpu.telemetry.multihost import process_info

            idx, count = process_info()
            stem = f"{run_name}_p{idx}" if count > 1 else run_name
            self._jsonl = open(path / f"{stem}_metrics.jsonl", "a")

    def log_image(self, step: int, name: str, fig) -> Optional[Path]:
        """Log a matplotlib figure: a wandb image when wandb is live, a PNG
        under ``<out_dir>/images/`` otherwise (the in-training dashboard
        channel — reference `big_sweep.py:87-157` logs MMCS grids and
        sparsity histograms as wandb images every 10 chunks).

        Returns the written path (None on the wandb path). The caller owns
        the figure (close it after logging)."""
        if self._wandb is not None:
            import wandb

            # no explicit step: scalar logging advances the wandb run step
            # per BATCH, while images arrive per CHUNK — an explicit smaller
            # step would trip wandb's monotonic-step rule and be dropped.
            # The chunk index rides alongside as its own metric.
            self._wandb.log({name: wandb.Image(fig), f"{name}_chunk": int(step)})
            return None
        if self._out_dir is None:
            return None
        img_dir = self._out_dir / "images"
        img_dir.mkdir(parents=True, exist_ok=True)
        path = img_dir / f"{name}_{int(step)}.png"
        fig.savefig(path, dpi=110, bbox_inches="tight")
        return path

    def log(self, step: int, tree: Dict[str, jax.Array]):
        """Queue a pytree of [n_models]-shaped device scalars. No host sync."""
        self._buffer.append((step, tree))

    def flush(self):
        if not self._buffer:
            return
        steps = [s for s, _ in self._buffer]
        # ONE transfer — and THE sanctioned host-sync point of the hot loop,
        # exempt from any enclosing telemetry.audit.transfer_audit
        with allowed_transfer():
            trees = jax.device_get([t for _, t in self._buffer])
        now = time.time()
        for step, tree in zip(steps, trees):
            for metric, values in tree.items():
                vals = values.reshape(-1) if getattr(values, "ndim", 0) else [values]
                for m, v in enumerate(vals):
                    series = (
                        self.model_names[m]
                        if self.model_names and m < len(self.model_names)
                        else f"model_{m}"
                    )
                    rec = {
                        "step": int(step),
                        "series": series,
                        "metric": metric,
                        "value": float(v),
                        "ts": now,
                    }
                    if self._wandb is not None:
                        self._wandb.log({f"{series}_{metric}": float(v)}, step=int(step))
                    if self._jsonl is not None:
                        self._jsonl.write(json.dumps(rec) + "\n")
        if self._jsonl is not None:
            self._jsonl.flush()
        self._buffer.clear()
        if self.on_flush is not None:
            # after the disk write + buffer clear: an aborting guard leaves
            # the window persisted and close() won't re-log it
            self.on_flush(steps, trees)

    def close(self):
        self.flush()
        if self._jsonl is not None:
            self._jsonl.close()
        if self._wandb is not None:
            self._wandb.finish()
