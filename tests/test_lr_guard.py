"""The dead-ensemble watchdog (LR_COLLAPSE study follow-up, VERDICT r2 #3):
`ensemble_train_loop` warns loudly when every member's codes are all-zero,
and stays silent on live ensembles."""

import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sparse_coding__tpu.ensemble import build_ensemble
from sparse_coding__tpu.models import FunctionalTiedSAE
from sparse_coding__tpu.train.loop import ensemble_train_loop, warn_if_ensemble_dead


def _ens(bias=0.0):
    ens = build_ensemble(
        FunctionalTiedSAE,
        jax.random.PRNGKey(0),
        [{"l1_alpha": 1e-4}, {"l1_alpha": 1e-3}],
        optimizer_kwargs={"learning_rate": 1e-3},
        activation_size=16,
        n_dict_components=64,
    )
    if bias:
        ens.state.params["encoder_bias"] = (
            jnp.full_like(ens.state.params["encoder_bias"], bias)
        )
    return ens


def test_live_ensemble_no_warning():
    ens = _ens()
    data = jax.random.normal(jax.random.PRNGKey(1), (128, 16))
    with warnings.catch_warnings():
        warnings.simplefilter("error", RuntimeWarning)
        ensemble_train_loop(ens, data, batch_size=32, key=jax.random.PRNGKey(2))


def test_dead_ensemble_warns():
    # a hugely negative encoder bias shuts every relu gate: all-zero codes,
    # exactly the collapse end-state
    ens = _ens(bias=-1e6)
    data = jax.random.normal(jax.random.PRNGKey(1), (128, 16))
    assert warn_if_ensemble_dead(ens, data)
    with pytest.warns(RuntimeWarning, match="DEAD ENSEMBLE"):
        ensemble_train_loop(
            ens, data, batch_size=32, key=jax.random.PRNGKey(2),
        )


def test_dead_check_can_be_disabled():
    ens = _ens(bias=-1e6)
    data = jax.random.normal(jax.random.PRNGKey(1), (128, 16))
    with warnings.catch_warnings():
        warnings.simplefilter("error", RuntimeWarning)
        ensemble_train_loop(
            ens, data, batch_size=32, key=jax.random.PRNGKey(2), dead_check=False
        )
