"""Worker for tests/test_multiprocess.py — one simulated 'host' of a pod.

Each process owns 8//n_proc virtual CPU devices; together the processes form
an 8-device global mesh (2 procs x 4 devices or 4 procs x 2 — the pod
topology is a parameter). The worker builds the framework's (model, data, dict) mesh over
the GLOBAL device set, shards an ensemble across it, feeds a globally-sharded
batch through `parallel.distributed.host_local_to_global` (each process
contributing its `local_batch_slice`), steps, and prints the all-gathered
losses — which the parent compares against a single-process reference run.
"""

import os
import sys


def worker_config(mode: str):
    """(d_act, n_dict, batch, mesh_shape) per mode — shared with the parent
    test's single-process reference run."""
    if mode == "dictpar":
        # 32x-overcomplete (config-5 geometry scaled down), dict axis 4
        return 64, 2048, 64, (1, 2, 4)
    return 32, 128, 64, (2, 2, 2)


def main():
    proc_id, n_proc, coord = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
    # "default": 4-member tied SAE on the (2,2,2) mesh.
    # "dictpar": the BASELINE config-5 analogue — 32x-overcomplete dict
    #   sharded over a dict=4 axis that stays WITHIN each host, data=2 axis
    #   crossing the host (DCN) boundary: the real pod layout for dictpar
    #   (VERDICT r4 next #6).
    # "telemetry": ISSUE-4 pod observability over the same gloo coordination
    #   layer (per-process events.p<i>.jsonl into the shared run dir
    #   argv[5], desync check, per-chunk heartbeats + skew, clock offset).
    #   Training is host-local in this mode: the telemetry exchanges ride
    #   jax's distributed KV store, which works on CPU+gloo, while
    #   cross-process XLA computations do not on this jaxlib ("Multiprocess
    #   computations aren't implemented on the CPU backend") — exactly the
    #   situation the KV transport exists for. Knobs via env:
    #   SC_TEST_CHUNK_SLEEP=<s> makes THIS host a straggler (sleeps inside
    #   each chunk), SC_TEST_DESYNC=1 poisons the run config with the
    #   process id so hosts deliberately disagree.
    mode = sys.argv[4] if len(sys.argv) > 4 else "default"
    dpp = 8 // n_proc  # devices per simulated host
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count={dpp}"
    ).strip()
    import jax

    jax.config.update("jax_platforms", "cpu")

    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    from sparse_coding__tpu.parallel.distributed import (
        initialize_distributed,
        local_batch_slice,
    )

    assert initialize_distributed(coord, n_proc, proc_id)
    assert jax.process_count() == n_proc
    assert len(jax.devices()) == 8

    import numpy as np
    from jax.experimental import multihost_utils

    from sparse_coding__tpu import build_ensemble
    from sparse_coding__tpu.models import FunctionalTiedSAE
    from sparse_coding__tpu.parallel import make_mesh
    from sparse_coding__tpu.parallel.mesh import batch_sharding

    if mode == "telemetry":
        telemetry_main(proc_id)
        return

    d_act, n_dict, batch, mesh_shape = worker_config(mode)
    ens = build_ensemble(
        FunctionalTiedSAE,
        jax.random.PRNGKey(0),
        [{"l1_alpha": a} for a in (1e-4, 3e-4, 1e-3, 3e-3)],
        optimizer_kwargs={"learning_rate": 1e-3},
        activation_size=d_act,
        n_dict_components=n_dict,
    )
    mesh = make_mesh(*mesh_shape)  # spans all processes: 8 global devices
    ens.shard(mesh)
    # members + dict components live across processes
    assert not ens.state.params["encoder"].is_fully_addressable

    # the host-side loader contract: each process holds only its batch slice
    sl = local_batch_slice(batch)
    assert (sl.stop - sl.start) * n_proc == batch

    sharding = batch_sharding(mesh)
    for step in range(3):
        # every process derives the same global batch (as a pod data loader
        # with a shared seed would); each addressable shard pulls its rows
        full = np.asarray(
            jax.random.normal(jax.random.PRNGKey(100 + step), (batch, d_act))
        )
        gbatch = jax.make_array_from_callback(
            (batch, d_act), sharding, lambda idx: full[idx]
        )
        loss_dict, _ = ens.step_batch(gbatch)  # presharded: passes through

    losses = multihost_utils.process_allgather(loss_dict["loss"], tiled=True)
    print("LOSSES=" + ",".join(f"{v:.8f}" for v in np.asarray(losses).reshape(-1)))


def telemetry_main(proc_id: int):
    """ISSUE-4 pod-telemetry drill: host-local training, REAL cross-process
    telemetry (KV-store clock offset / desync digests / heartbeat skew),
    per-process logs into the shared run dir."""
    import time

    import jax
    import numpy as np

    from sparse_coding__tpu import build_ensemble
    from sparse_coding__tpu.models import FunctionalTiedSAE
    from sparse_coding__tpu.telemetry import RunTelemetry, check_desync, heartbeat

    run_dir = sys.argv[5]
    from sparse_coding__tpu.utils import flags

    sleep_s = flags.SC_TEST_CHUNK_SLEEP.get() or 0.0
    d_act, batch = 16, 64
    cfg = {"mode": "telemetry", "batch": batch, "d_act": d_act}
    if flags.SC_TEST_DESYNC.get():
        cfg["poison"] = proc_id  # hosts now deliberately disagree
    ens = build_ensemble(
        FunctionalTiedSAE,
        jax.random.PRNGKey(0),
        [{"l1_alpha": a} for a in (1e-4, 1e-3)],
        optimizer_kwargs={"learning_rate": 1e-3},
        activation_size=d_act,
        n_dict_components=4 * d_act,
    )
    telemetry = RunTelemetry(out_dir=run_dir, run_name="podtest", config=cfg)
    telemetry.run_start()
    check_desync(telemetry, config=cfg)  # warn-only: the run continues
    for step in range(3):
        telemetry.chunk_start(step)
        if sleep_s:
            time.sleep(sleep_s)  # injected straggler
        batch_arr = jax.random.normal(
            jax.random.PRNGKey(100 + step), (batch, d_act)
        )
        loss_dict, _ = ens.step_batch(batch_arr)
        jax.block_until_ready(loss_dict["loss"])
        telemetry.counter_inc("train.steps")
        end_rec = telemetry.chunk_end(step)
        heartbeat(telemetry, step=step + 1, window_seconds=end_rec.get("seconds"))
    telemetry.run_end(status="ok")
    telemetry.close()
    print("TELEMETRY_OK")


if __name__ == "__main__":
    main()
