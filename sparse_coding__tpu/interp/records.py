"""Activation records + explanation scoring (the OpenAI autointerp protocol).

Counterpart of the `neuron_explainer` machinery the reference drives in
`interpret.py:265-386`: per-feature activation records over text fragments,
explanation simulation, and the "preferred score" — the correlation between
simulated and true activations (Bills et al. 2023). Re-implemented here as
plain dataclasses + numpy so the pipeline runs without the neuron-explainer
package; the LLM calls live behind `interp.clients`.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence

import numpy as np

# protocol constants (reference `interpret.py:50-57`)
OPENAI_MAX_FRAGMENTS = 50000
OPENAI_FRAGMENT_LEN = 64
OPENAI_EXAMPLES_PER_SPLIT = 5
N_SPLITS = 4
TOTAL_EXAMPLES = OPENAI_EXAMPLES_PER_SPLIT * N_SPLITS
REPLACEMENT_CHAR = "�"


@dataclasses.dataclass
class ActivationRecord:
    tokens: List[str]
    activations: List[float]


@dataclasses.dataclass
class NeuronRecord:
    """Top + random activation records for one feature
    (reference `interpret.py:324-330`)."""

    feature_index: int
    most_positive_activation_records: List[ActivationRecord]
    random_sample: List[ActivationRecord]

    def train_records(self, per_split: int = OPENAI_EXAMPLES_PER_SPLIT) -> List[ActivationRecord]:
        """Half the top + half the random records (explainer input)."""
        return (
            self.most_positive_activation_records[:per_split]
            + self.random_sample[:per_split]
        )

    def valid_records(self, per_split: int = OPENAI_EXAMPLES_PER_SPLIT) -> List[ActivationRecord]:
        """Held-out top + random records (simulator scoring input)."""
        return (
            self.most_positive_activation_records[per_split : 2 * per_split]
            + self.random_sample[per_split : 2 * per_split]
        )


def calculate_max_activation(records: Sequence[ActivationRecord]) -> float:
    return max((max(r.activations) for r in records), default=0.0)


@dataclasses.dataclass
class SequenceSimulation:
    tokens: List[str]
    true_activations: List[float]
    simulated_activations: List[float]


@dataclasses.dataclass
class ScoredSimulation:
    explanation: str
    sequence_simulations: List[SequenceSimulation]

    def get_preferred_score(self) -> float:
        return aggregate_scored_sequence_simulations(self.sequence_simulations)


def aggregate_scored_sequence_simulations(
    sims: Sequence[SequenceSimulation],
) -> float:
    """Correlation between simulated and true activations, pooled over all
    sequences — the protocol's preferred score (ev_correlation_score)."""
    true = np.concatenate([np.asarray(s.true_activations, dtype=np.float64) for s in sims])
    pred = np.concatenate([np.asarray(s.simulated_activations, dtype=np.float64) for s in sims])
    if true.std() < 1e-9 or pred.std() < 1e-9:
        return 0.0
    return float(np.corrcoef(true, pred)[0, 1])
