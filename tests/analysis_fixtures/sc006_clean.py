"""Fixture: SC006 clean twin — distinct names stay distinct after
sanitization; a counter and a gauge may share a stem (the counter gets
``_total``)."""


def publish(gauge_set, counter_inc, depth):
    gauge_set("serve.queue.depth", depth)
    counter_inc("serve.queue.depth")
    gauge_set("serve.batch.rows", depth)
