"""CLI shim: ``python -m sparse_coding__tpu.scrub <store> [--repair CFG]``.

Offline chunk-store integrity scrub: re-verifies every committed chunk
at the digest tier, quarantines failures, and (``--repair``) re-harvests
exact missing indices from a repair config. Exit 1 while unrepaired loss
remains — the dataplane's CI gate, and the producer of the quarantine
ledgers `python -m sparse_coding__tpu.lineage` reads as taint sources.
Implementation: `sparse_coding__tpu.data.scrub` (docs/DATAPLANE.md).
"""

from sparse_coding__tpu.data.scrub import (
    main,
    render_scrub_markdown,
    repair_from_config,
    scrub_store,
    store_loss,
)

__all__ = [
    "main",
    "render_scrub_markdown",
    "repair_from_config",
    "scrub_store",
    "store_loss",
]

if __name__ == "__main__":
    raise SystemExit(main())
