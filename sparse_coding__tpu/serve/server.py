"""Stdlib HTTP front end for the encode engine, with graceful SIGTERM drain.

``python -m sparse_coding__tpu.serve.server <export> [--port 0] ...`` loads
learned-dict exports into a `DictRegistry`, warms the engine's compiled
steps, and serves the API (docs/SERVING.md):

  - ``POST /encode``  — ``{"dict": "<id>", "rows": [[...], ...]}`` →
    ``{"dict", "n_rows", "codes", "latency_ms"}``. Unknown dict → 404;
    malformed rows → 400; draining → **503 with Retry-After and
    ``{"retryable": true}``** — the clean hand-back a load balancer retries
    against another replica. **Content negotiation** (ISSUE 15,
    `serve.wire`): request bodies and responses ride any of JSON
    (default), npz (``application/x-npz``), or the raw little-endian
    format (``application/x-sc-raw``) — ``Content-Type`` names the request
    format, ``Accept`` picks the response format, and array dtypes travel
    exactly in every format. ``"top_k": k`` in the request meta switches
    the response to sparse ``indices`` + ``values`` (k clamped to the
    dict's n_feats, computed inside the compiled step).
  - ``POST /features`` — ``{"dict": "<id>", "tokens": [[...ids...]]}``
    (or ``"texts"`` when the attached subject tokenizes): fused subject-LM
    capture + dict encode in ONE dispatch (`registry.SubjectLM`), returning
    codes — dense or top-k sparse — for every token position. Same wire
    negotiation as /encode.
  - ``GET /dicts``    — registry metadata (id, class, shape, residency)
    plus attached subjects.
  - ``GET /healthz``  — ``{"status": "ok"|"draining", "queue_depth", ...}``.

**Drain protocol** (the PR-5 preemption machinery, re-used): SIGTERM/SIGINT
set the host-side preemption flag (`train.preemption.install_signal_handlers`
+ `poller_started` — same handler the training drivers install). The serve
loop polls the flag; when set it (1) flips the engine to rejecting (new
``/encode`` → retryable 503), (2) drains every request already accepted
(`EncodeEngine.stop(drain=True)` — in-flight requests COMPLETE), (3) keeps
answering 503s while draining, then shuts the listener down and exits **0**.
A served request is never dropped: it either returns 200 with its codes or
was never accepted. tests/test_serve.py's chaos test SIGTERMs a loaded
server and asserts exactly that.

`ServeClient` is the stdlib in-process client the tests and
`scripts/loadgen.py` use; `ServeServer` runs the same server in-process on
an ephemeral port.
"""

from __future__ import annotations

import argparse
import json
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from pathlib import Path
from typing import Any, Dict, List, Optional

import numpy as np

from sparse_coding__tpu.serve.engine import EncodeEngine, EngineClosed
from sparse_coding__tpu.serve.registry import DictRegistry

__all__ = ["ServeServer", "ServeClient", "main"]


class _Handler(BaseHTTPRequestHandler):
    # the ThreadingHTTPServer instance carries .serve (ServeServer)
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):  # stdlib default spams stderr
        if self.server.serve.verbose:
            sys.stderr.write(f"[serve] {fmt % args}\n")

    def _json(self, code: int, payload: Dict[str, Any],
              headers: Optional[Dict[str, str]] = None) -> None:
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def _reject_draining(self) -> None:
        self._json(
            503,
            {"error": "draining", "retryable": True,
             "detail": "server is draining for shutdown — retry elsewhere"},
            headers={"Retry-After": "1"},
        )

    def do_GET(self):
        srv = self.server.serve
        if self.path == "/healthz":
            self._json(200, srv.health())
            return
        if self.path == "/dicts":
            self._json(200, {"dicts": srv.registry.describe(),
                             "subjects": srv.registry.describe_subjects()})
            return
        if self.path == "/metrics":
            body = srv.metrics_text().encode()
            from sparse_coding__tpu.telemetry.metrics_http import CONTENT_TYPE

            self.send_response(200)
            self.send_header("Content-Type", CONTENT_TYPE)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        self._json(404, {"error": f"no route {self.path}"})

    def do_POST(self):
        srv = self.server.serve
        if self.path not in ("/encode", "/features"):
            self._json(404, {"error": f"no route {self.path}"})
            return
        if srv.draining:
            self._reject_draining()
            return
        from sparse_coding__tpu.serve import wire

        fmt_in = wire.format_of_content_type(self.headers.get("Content-Type"))
        fmt_out = wire.negotiate(self.headers.get("Accept"))
        try:
            length = int(self.headers.get("Content-Length", 0))
            raw = self.rfile.read(length)
            arrays, meta = wire.decode_payload(fmt_in, raw)
            dict_id = meta["dict"]
            top_k = meta.get("top_k")
            if top_k is not None:
                top_k = int(top_k)
        except (ValueError, KeyError, TypeError) as e:
            self._json(400, {"error": f"bad request: {e}"})
            return
        # trace propagation (docs/observability.md §8): an X-Trace-Id'd
        # request gets a fresh server-hop span parented on the caller's
        # X-Parent-Span (the router's attempt span), threaded into the
        # engine so its request_trace record joins the caller's tree
        from sparse_coding__tpu.telemetry.tracing import TraceContext

        trace = TraceContext.from_headers(self.headers)
        trace_headers = (
            {"X-Trace-Id": trace.trace_id} if trace is not None else None
        )
        t0 = time.monotonic()
        try:
            if self.path == "/features":
                tokens = self._feature_tokens(srv, arrays, meta)
                out = srv.engine.encode_features(
                    dict_id, tokens, subject=meta.get("subject"),
                    timeout=srv.request_timeout, trace=trace, top_k=top_k,
                )
            else:
                rows = arrays.get("rows")
                if rows is None:
                    rows = meta.get("rows")  # plain-JSON compat (no __dtypes__)
                if rows is None:
                    raise ValueError("request carries no 'rows'")
                out = srv.engine.encode(
                    dict_id, rows, timeout=srv.request_timeout, trace=trace,
                    top_k=top_k,
                )
        except EngineClosed:
            self._reject_draining()
            return
        except KeyError as e:
            self._json(404, {"error": f"unknown dict or subject: {e}",
                             "dicts": srv.registry.ids(),
                             "subjects": srv.registry.subjects()},
                       headers=trace_headers)
            return
        except (ValueError, TypeError) as e:
            self._json(400, {"error": str(e)}, headers=trace_headers)
            return
        except TimeoutError as e:
            self._json(504, {"error": str(e), "retryable": True},
                       headers=trace_headers)
            return
        if top_k is None:
            out_arrays = {"codes": np.asarray(out)}
            n_rows = int(out_arrays["codes"].shape[0])
        else:
            idx, vals = out
            out_arrays = {"indices": np.asarray(idx), "values": np.asarray(vals)}
            n_rows = int(out_arrays["values"].shape[0])
        out_meta = {
            "dict": dict_id,
            "n_rows": n_rows,
            "latency_ms": round((time.monotonic() - t0) * 1e3, 3),
            "generation": srv.dict_generation,
        }
        if top_k is not None:
            out_meta["sparse"] = True
            out_meta["k"] = int(out_arrays["values"].shape[1])
        if trace is not None:
            out_meta["trace_id"] = trace.trace_id
        body = wire.encode_payload(fmt_out, out_arrays, out_meta)
        self.send_response(200)
        self.send_header("Content-Type", wire.CONTENT_TYPES[fmt_out])
        self.send_header("Content-Length", str(len(body)))
        for k, v in (trace_headers or {}).items():
            self.send_header(k, v)
        prov = srv.registry.provenance_digest()
        if prov:
            self.send_header("X-Dict-Provenance", prov)
        self.end_headers()
        self.wfile.write(body)
        srv.note_wire(self.path, fmt_in, fmt_out, len(raw), len(body),
                      out_meta["latency_ms"])

    @staticmethod
    def _feature_tokens(srv, arrays, meta):
        """Token rows for a /features request: int ``tokens`` ride any wire
        format; ``texts`` (list of strings) tokenizes through the subject's
        attached tokenizer with the harvest pipeline's EOS-joined exact-
        length chunking (`data.activations.chunk_and_tokenize_texts`)."""
        tokens = arrays.get("tokens")
        if tokens is None:
            tokens = meta.get("tokens")  # plain-JSON compat
        if tokens is not None:
            return tokens
        texts = meta.get("texts")
        if texts is None:
            raise ValueError("request carries neither 'tokens' nor 'texts'")
        subj = srv.registry.get_subject(meta.get("subject"))
        if subj.tokenize is None:
            raise ValueError(
                f"subject {subj.subject_id!r} has no tokenizer attached — "
                "send 'tokens' instead of 'texts'"
            )
        from sparse_coding__tpu.data.activations import chunk_and_tokenize_texts

        toks = chunk_and_tokenize_texts(
            [str(t) for t in texts], subj.tokenize,
            eos_id=int(meta.get("eos_id", 0)),
            max_length=int(meta.get("seq_len", 128)),
        )
        if toks.shape[0] == 0:
            raise ValueError(
                "texts tokenized to fewer than seq_len tokens — nothing to "
                "encode (send more text or a smaller 'seq_len')"
            )
        return toks


class ServeServer:
    """The serving process object: registry + engine + HTTP listener.

    In-process use (tests, loadgen)::

        with ServeServer(registry) as srv:
            client = srv.client()
            codes = client.encode("d0", rows)

    Process use: `main` — which adds the SIGTERM drain loop.
    """

    def __init__(
        self,
        registry: DictRegistry,
        host: str = "127.0.0.1",
        port: int = 0,
        engine: Optional[EncodeEngine] = None,
        telemetry=None,
        request_timeout: float = 60.0,
        verbose: bool = False,
        dict_generation: int = 0,
        replica_id: Optional[str] = None,
        feature_baseline=None,
        feature_flush_s: float = 30.0,
        drift_policy=None,
        **engine_kwargs,
    ):
        self.registry = registry
        self.telemetry = telemetry
        self.engine = engine or EncodeEngine(
            registry, telemetry=telemetry, **engine_kwargs
        )
        self.request_timeout = float(request_timeout)
        self.verbose = verbose
        # feature-level observability (docs/observability.md §10): when the
        # engine carries a firing sketch (``feature_stats=True`` engine
        # kwarg), this server owns its flush cadence — scrape-driven via
        # `metrics_text` plus the drain boundary, min `feature_flush_s`
        # apart — and runs the train↔serve drift check against
        # `feature_baseline` (a FeatureSnapshot or path to one) through an
        # `AnomalyGuard`. An abort-tier drift sets `drift_abort_requested`
        # instead of raising into a scrape handler; `main`'s loop drains on
        # it (in-process embedders poll it themselves).
        self.feature_flush_s = float(feature_flush_s)
        self.feature_guard = None
        self.drift_abort_requested = False
        fs = getattr(self.engine, "feature_stats", None)
        if fs is not None:
            if feature_baseline is not None:
                from sparse_coding__tpu.telemetry.feature_stats import (
                    FeatureSnapshot,
                )

                if not isinstance(feature_baseline, FeatureSnapshot):
                    feature_baseline = FeatureSnapshot.load(feature_baseline)
                fs.set_baseline(feature_baseline)
            from sparse_coding__tpu.telemetry.anomaly import AnomalyGuard

            out_dir = (
                telemetry.path.parent
                if telemetry is not None and telemetry.path is not None
                else None
            )
            self.feature_guard = AnomalyGuard(
                telemetry=telemetry,
                out_dir=out_dir,
                policy=drift_policy,
                model_names=registry.ids(),
            )
        # the dict generation this replica serves (a rolling swap relaunches
        # replicas with the next generation): stamped into every /encode
        # response so a client/router can SEE which rollout answered — the
        # torn-rollout detector the replica-tier chaos test asserts on
        self.dict_generation = int(dict_generation)
        self.replica_id = replica_id
        self.draining = False
        # wire accounting (ISSUE 15): bytes + request counts per response
        # format — the report's wire line and the bench bytes/row evidence
        self._wire_lock = threading.Lock()
        self.wire_stats: Dict[str, Dict[str, float]] = {}
        self._t0 = time.time()
        self.httpd = ThreadingHTTPServer((host, port), _Handler)
        self.httpd.daemon_threads = True
        self.httpd.serve = self  # handler back-reference
        self._http_thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self.httpd.server_address[1]

    @property
    def address(self) -> str:
        host, port = self.httpd.server_address[:2]
        return f"http://{host}:{port}"

    def start(self) -> "ServeServer":
        self.engine.start()
        self._http_thread = threading.Thread(
            target=self.httpd.serve_forever, daemon=True, name="serve-http"
        )
        self._http_thread.start()
        return self

    def note_wire(self, endpoint: str, fmt_in: str, fmt_out: str,
                  bytes_in: int, bytes_out: int, latency_ms: float) -> None:
        """Per-format wire accounting for one answered request:
        ``serve.bytes_in/out.<fmt>`` + ``serve.requests.<fmt>`` counters
        and a per-format latency histogram
        (``serve.format.<fmt>.latency_ms``) on the telemetry bus, mirrored
        into `wire_stats` for telemetry-less servers."""
        # bytes_in belongs to the REQUEST format, requests/bytes_out to the
        # response format — mirroring the telemetry counters exactly, so a
        # cross-format request (raw in, json out) books identically in both
        with self._wire_lock:
            def _slot(fmt):
                return self.wire_stats.setdefault(
                    fmt, {"requests": 0, "bytes_in": 0, "bytes_out": 0}
                )

            out_slot = _slot(fmt_out)
            out_slot["requests"] += 1
            out_slot["bytes_out"] += int(bytes_out)
            _slot(fmt_in)["bytes_in"] += int(bytes_in)
        if self.telemetry is not None:
            self.telemetry.counter_inc(f"serve.requests.{fmt_out}")
            self.telemetry.counter_inc(f"serve.bytes_in.{fmt_in}", int(bytes_in))
            self.telemetry.counter_inc(f"serve.bytes_out.{fmt_out}", int(bytes_out))
            self.telemetry.hist_observe(
                f"serve.format.{fmt_out}.latency_ms", float(latency_ms)
            )

    def health(self) -> Dict[str, Any]:
        """The enriched healthz body (ISSUE 13): everything a router health
        probe needs in ONE response — queue depth, batch occupancy, the
        registry generation (hot-swap watermark), the dict generation
        (rolling-rollout watermark), and the draining flag — previously
        these existed only as internal gauges."""
        lat = self.engine.latency_snapshot()
        stats = self.engine.stats
        out = {
            "status": "draining" if self.draining else "ok",
            "draining": self.draining,
            "dicts": len(self.registry),
            "queue_depth": self.engine.queue_depth,
            "batch_occupancy": self.engine.batch_occupancy,
            "registry_generation": self.registry.generation,
            "dict_generation": self.dict_generation,
            "requests": stats["requests"],
            "rejected": stats["rejected"],
            "errors": stats["errors"],
            "uptime_seconds": round(time.time() - self._t0, 3),
            "latency_p50_ms": round(lat["p50_ms"], 3),
            "latency_p99_ms": round(lat["p99_ms"], 3),
            "subjects": self.registry.subjects(),
            "dict_provenance": self.registry.provenance_digest(),
        }
        if self.replica_id is not None:
            out["replica"] = self.replica_id
        return out

    def maybe_flush_features(self, force: bool = False) -> List[Dict[str, Any]]:
        """Flush the engine's firing sketch into ``feature_stats.serveNNNN.npz``
        snapshots (+ gauges + pointer events) and run the drift check — when
        the engine carries one, a run dir exists, and at least
        `feature_flush_s` elapsed since the last flush (``force`` overrides
        the interval: the drain boundary must not drop a partial window).
        Returns the per-snapshot summaries."""
        fs = getattr(self.engine, "feature_stats", None)
        if fs is None:
            return []
        if self.telemetry is None or self.telemetry.path is None:
            return []
        if not force and fs.seconds_since_flush < self.feature_flush_s:
            return []
        extra: Dict[str, Any] = {"dict_generation": self.dict_generation}
        if self.replica_id is not None:
            extra["replica"] = self.replica_id
        summaries = fs.flush(self.telemetry, self.telemetry.path.parent, extra=extra)
        if self.feature_guard is not None:
            from sparse_coding__tpu.telemetry.anomaly import AnomalyAbort

            for s in summaries:
                if "drift_score" not in s:
                    continue
                try:
                    self.feature_guard.observe_feature_drift(
                        s["drift_score"],
                        top=s.get("drift_top"),
                        scope="serve",
                        baseline=fs.baseline.gen if fs.baseline else None,
                        current=s["gen"],
                    )
                except AnomalyAbort:
                    # never raise into a scrape/drain path: flag it and let
                    # the serving loop (or the embedder) drain gracefully
                    self.drift_abort_requested = True
        return summaries

    def metrics_text(self) -> str:
        """The ``GET /metrics`` body: Prometheus text exposition of this
        replica's counters/gauges/histograms (docs/observability.md §8).
        With telemetry, the full bus (labeled by the replica tag) plus
        freshly-sampled queue/occupancy gauges; without, a minimal set
        derived from the engine's stats so the endpoint always answers."""
        from sparse_coding__tpu.telemetry.metrics_http import (
            render_prometheus,
            telemetry_metrics_text,
        )

        self.maybe_flush_features()
        if self.telemetry is not None:
            self.telemetry.gauge_set("serve.queue_depth", self.engine.queue_depth)
            self.telemetry.gauge_set(
                "serve.batch_occupancy", self.engine.batch_occupancy
            )
            self.telemetry.gauge_set("serve.draining", float(self.draining))
            return telemetry_metrics_text(self.telemetry)
        lat = self.engine.latency_snapshot()
        stats = self.engine.stats
        labels = {"replica": self.replica_id} if self.replica_id else None
        return render_prometheus(
            counters={f"serve.{k}": v for k, v in stats.items()},
            gauges={
                "serve.queue_depth": self.engine.queue_depth,
                "serve.batch_occupancy": self.engine.batch_occupancy,
                "serve.latency_p50_ms": lat["p50_ms"],
                "serve.latency_p95_ms": lat["p95_ms"],
                "serve.latency_p99_ms": lat["p99_ms"],
                "serve.draining": float(self.draining),
            },
            labels=labels,
        )

    def drain(self, timeout: float = 60.0) -> None:
        """The graceful half of shutdown: reject new encodes (503), complete
        everything already accepted. The listener stays up (answering 503s
        and health checks) until `close`."""
        self.draining = True
        if self.telemetry is not None:
            self.telemetry.event(
                "serve_drain", queue_depth=self.engine.queue_depth
            )
        self.engine.stop(drain=True, timeout=timeout)
        # the drained batches' firing stats must reach disk before shutdown
        self.maybe_flush_features(force=True)

    def close(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()

    def stop(self, timeout: float = 60.0) -> None:
        self.drain(timeout=timeout)
        self.close()

    def client(self, timeout: float = 30.0) -> "ServeClient":
        return ServeClient(self.address, timeout=timeout)

    def __enter__(self) -> "ServeServer":
        return self.start()

    def __exit__(self, exc_type, exc, tb):
        self.stop()
        return False


class RetryableRejection(RuntimeError):
    """A clean 503/"draining" hand-back: safe to retry against a replica.
    ``retry_after`` carries the server's Retry-After hint (seconds, 0.0
    when absent) — retry loops use it as a floor on their backoff."""

    retry_after: float = 0.0


class ServeClient:
    """Minimal stdlib HTTP client (tests, loadgen — no deps).

    ``retries > 1`` makes `encode` retry clean retryable rejections
    (draining 503s, 504 timeouts with ``retryable: true``) through the
    repo-wide `utils.sync.retry_with_backoff` engine — same schedule as
    chunk reads and remote syncs, honoring the server's ``Retry-After`` as
    a floor on each sleep and bumping a ``serve.client.retry`` counter on
    the active telemetry. Connection errors are NOT retried here: against
    a single server they mean it is gone; `serve.router.RouterClient`
    fronting a replica set is the layer that retries those (elsewhere).

    Wire formats (ISSUE 15): ``format="json"|"npz"|"raw"`` selects the
    request body AND ``Accept`` content type (`serve.wire`). Responses
    round-trip dtype exactly in every format — the old silent
    ``dtype=np.float32`` coercion is gone; a bf16 dict's codes come back
    bf16. ``top_k=k`` returns sparse ``(indices, values)``. Bytes on the
    wire are counted into `bytes_sent` / `bytes_received` (loadgen's
    bytes-per-row accounting reads them)."""

    def __init__(self, base_url: str, timeout: float = 30.0,
                 retries: int = 1, backoff_base: float = 0.05):
        self.base_url = base_url.rstrip("/")
        self.timeout = timeout
        self.retries = max(1, int(retries))
        self.backoff_base = float(backoff_base)
        self._bytes_lock = threading.Lock()
        self.bytes_sent = 0
        self.bytes_received = 0

    def _note_bytes(self, sent: int, received: int) -> None:
        with self._bytes_lock:
            self.bytes_sent += int(sent)
            self.bytes_received += int(received)

    def bytes_snapshot(self) -> Dict[str, int]:
        with self._bytes_lock:
            return {"bytes_sent": self.bytes_sent,
                    "bytes_received": self.bytes_received}

    def _retryable_exc(self, payload: Dict[str, Any],
                       headers: Dict[str, str]) -> RetryableRejection:
        """Build the retryable-rejection exception for a 503/504 hand-back
        (subclasses refine the type — `RouterClient` raises ShedRejection
        for router sheds)."""
        exc = RetryableRejection(payload.get("error", "rejected"))
        try:
            exc.retry_after = float(headers.get("Retry-After", 0) or 0)
        except (TypeError, ValueError):
            exc.retry_after = 0.0
        return exc

    def _request_full(
        self, method: str, path: str,
        payload: Optional[Any] = None,
        headers: Optional[Dict[str, str]] = None,
        raw: bool = False,
    ) -> tuple:
        """One HTTP round trip; returns (body, response headers). ``payload``
        is a JSON-able dict or pre-encoded ``bytes`` (binary wire formats —
        set the Content-Type via ``headers``). The success body is parsed
        JSON unless ``raw=True`` (wire callers decode per Content-Type);
        error bodies are always JSON, the server's error contract."""
        import urllib.error
        import urllib.request

        if isinstance(payload, (bytes, bytearray)):
            data: Optional[bytes] = bytes(payload)
        elif payload is None:
            data = None
        else:
            data = json.dumps(payload).encode()
        req = urllib.request.Request(
            self.base_url + path,
            data=data,
            headers={"Content-Type": "application/json", **(headers or {})},
            method=method,
        )
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                body = resp.read()
                self._note_bytes(len(data or b""), len(body))
                if raw:
                    return body, dict(resp.headers.items())
                return json.loads(body), dict(resp.headers.items())
        except urllib.error.HTTPError as e:
            raw_body = e.read()
            self._note_bytes(len(data or b""), len(raw_body))
            try:
                body = json.loads(raw_body)
            except Exception:
                body = {"error": str(e)}
            headers = dict(e.headers.items())
            if e.code in (503, 504) and body.get("retryable"):
                raise self._retryable_exc(body, headers)
            raise RuntimeError(f"HTTP {e.code}: {body.get('error')}") from e

    def _request(self, method: str, path: str,
                 payload: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
        return self._request_full(method, path, payload)[0]

    def _with_retries(self, fn):
        """Run `fn` under this client's retry policy: `retries` attempts of
        the shared backoff engine over clean retryable rejections only."""
        if self.retries <= 1:
            return fn()
        from sparse_coding__tpu.telemetry.events import counter_inc_active
        from sparse_coding__tpu.utils.sync import retry_with_backoff

        return retry_with_backoff(
            lambda _attempt: fn(),
            attempts=self.retries,
            base_delay=self.backoff_base,
            retry_on=(RetryableRejection,),
            on_retry=lambda a, e: counter_inc_active("serve.client.retry"),
            delay_floor_from=lambda e: getattr(e, "retry_after", 0.0),
        )

    @staticmethod
    def _trace_headers(trace) -> Optional[Dict[str, str]]:
        """``trace`` is a `telemetry.tracing.TraceContext`, a bare trace-id
        string, or None — normalized to the propagation headers."""
        if trace is None:
            return None
        if isinstance(trace, str):
            from sparse_coding__tpu.telemetry.tracing import TraceContext

            trace = TraceContext(trace)
        return trace.headers()

    def _wire_call(
        self, path: str, arrays: Dict[str, Any], meta: Dict[str, Any],
        fmt: str = "json", trace=None,
    ) -> tuple:
        """One wire-format POST: encode the ``(arrays, meta)`` payload in
        ``fmt``, Accept the same format back, decode the response per its
        Content-Type. Returns (out_arrays, out_meta, response_headers)."""
        from sparse_coding__tpu.serve import wire

        body = wire.encode_payload(
            fmt, {k: np.asarray(v) for k, v in arrays.items()}, meta
        )
        headers = {
            "Content-Type": wire.CONTENT_TYPES[fmt],
            "Accept": wire.CONTENT_TYPES[fmt],
            **(self._trace_headers(trace) or {}),
        }
        out, rheaders = self._with_retries(
            lambda: self._request_full("POST", path, body, headers=headers,
                                       raw=True)
        )
        out_arrays, out_meta = wire.decode_payload(
            wire.format_of_content_type(rheaders.get("Content-Type")), out
        )
        return out_arrays, out_meta, rheaders

    @staticmethod
    def _unpack_codes(out_arrays: Dict[str, np.ndarray],
                      out_meta: Optional[Dict[str, Any]] = None):
        """Dense codes or the sparse ``(indices, values)`` pair — dtypes
        exactly as the server computed them (the round-trip contract).
        Legacy JSON bodies (no ``__dtypes__`` — pre-wire servers) fall back
        to the historical f32 coercion."""
        if "codes" in out_arrays:
            return out_arrays["codes"]
        if "indices" in out_arrays:
            return out_arrays["indices"], out_arrays["values"]
        meta = out_meta or {}
        if "codes" in meta:
            return np.asarray(meta["codes"], dtype=np.float32)
        if "indices" in meta:
            return (np.asarray(meta["indices"], dtype=np.int32),
                    np.asarray(meta["values"], dtype=np.float32))
        raise KeyError("response carries no codes")

    def encode(self, dict_id: str, rows, trace=None, format: str = "json",
               top_k: Optional[int] = None):
        meta: Dict[str, Any] = {"dict": dict_id}
        if top_k is not None:
            meta["top_k"] = int(top_k)
        out_arrays, out_meta, _ = self._wire_call(
            "/encode", {"rows": rows}, meta, fmt=format, trace=trace
        )
        return self._unpack_codes(out_arrays, out_meta)

    def encode_topk(self, dict_id: str, rows, k: int, trace=None,
                    format: str = "json"):
        """Sparse encode: ``(indices int32 [n, k], values [n, k])``."""
        return self.encode(dict_id, rows, trace=trace, format=format,
                           top_k=int(k))

    def encode_features(self, dict_id: str, tokens=None, trace=None,
                        format: str = "json", top_k: Optional[int] = None,
                        subject: Optional[str] = None, texts=None,
                        seq_len: Optional[int] = None):
        """Fused harvest→encode over raw tokens (``[n_seq, seq_len]`` ints)
        or ``texts`` (needs a server-side tokenizer). Returns codes for
        every token position — dense or ``(indices, values)``."""
        meta: Dict[str, Any] = {"dict": dict_id}
        if top_k is not None:
            meta["top_k"] = int(top_k)
        if subject is not None:
            meta["subject"] = subject
        arrays: Dict[str, Any] = {}
        if tokens is not None:
            arrays["tokens"] = np.asarray(tokens, dtype=np.int32)
        elif texts is not None:
            meta["texts"] = list(texts)
            if seq_len is not None:
                meta["seq_len"] = int(seq_len)
        else:
            raise ValueError("pass tokens or texts")
        out_arrays, out_meta, _ = self._wire_call(
            "/features", arrays, meta, fmt=format, trace=trace
        )
        return self._unpack_codes(out_arrays, out_meta)

    def dicts(self) -> List[Dict[str, Any]]:
        return self._request("GET", "/dicts")["dicts"]

    def subjects(self) -> List[Dict[str, Any]]:
        return self._request("GET", "/dicts").get("subjects", [])

    def healthz(self) -> Dict[str, Any]:
        return self._request("GET", "/healthz")


def attach_subject_from_spec(registry: DictRegistry, spec: str,
                             subject_id: str = "subject"):
    """Attach a subject LM from a CLI spec:
    ``random:<model>:<layer>:<loc>[:seed]`` random-inits the named
    architecture (`lm.model.config_for` geometry) — the demo/bench path;
    production weights attach programmatically via
    `DictRegistry.attach_subject`."""
    kind, model, layer, rest = (str(spec).split(":", 3) + [""])[:4]
    loc, _, seed = rest.partition(":")
    if kind != "random":
        raise ValueError(f"unknown subject kind {kind!r} (want 'random:...')")
    import jax

    from sparse_coding__tpu.lm import model as lm_model

    lm_cfg = lm_model.config_for(model)
    params = lm_model.init_params(jax.random.PRNGKey(int(seed or 0)), lm_cfg)
    return registry.attach_subject(
        subject_id, params, lm_cfg, int(layer), layer_loc=loc or "residual",
        source=spec,
    )


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m sparse_coding__tpu.serve.server",
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument(
        "exports", nargs="+",
        help="learned-dict export(s): learned_dicts.pkl files or fleet run "
        "dirs with export_manifest.json",
    )
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=8777,
                    help="0 = ephemeral (see --port-file)")
    ap.add_argument("--port-file", default=None,
                    help="write the bound port here once listening "
                    "(subprocess tests / init systems)")
    ap.add_argument("--weights", choices=("native", "int8"), default="native",
                    help="weight residency for loaded dicts (int8 = chunk-"
                    "quant tier, half the resident bytes)")
    ap.add_argument("--max-batch", type=int, default=256)
    ap.add_argument("--max-wait-ms", type=float, default=2.0)
    ap.add_argument("--events", default=None, metavar="DIR",
                    help="write serve telemetry (events.jsonl) under DIR — "
                    "renderable with `python -m sparse_coding__tpu.report`")
    ap.add_argument("--replica-id", default=None,
                    help="this replica's id in a replica set (stamped into "
                    "every telemetry record and the healthz body)")
    ap.add_argument("--dict-generation", type=int, default=0,
                    help="the dict rollout generation this replica serves "
                    "(rolling swaps relaunch replicas with the next one); "
                    "stamped into every /encode response")
    ap.add_argument("--no-warmup", action="store_true",
                    help="skip bucket pre-compilation at startup")
    ap.add_argument("--warmup-topk", type=int, action="append", default=None,
                    metavar="K",
                    help="additionally pre-compile the fused top-k step for "
                    "this k (repeatable; ks share a power-of-two k-bucket "
                    "menu, so warming 16 covers every k in (8, 16])")
    ap.add_argument("--subject", default=None, metavar="SPEC",
                    help="attach a subject LM for POST /features. SPEC = "
                    "'random:<model>:<layer>:<loc>[:seed]' random-inits the "
                    "named architecture (demo/bench geometry; production "
                    "weights attach programmatically via "
                    "DictRegistry.attach_subject)")
    ap.add_argument("--subject-seq-len", type=int, default=32,
                    help="seq_len the /features warmup pre-compiles for")
    ap.add_argument("--feature-stats", action="store_true",
                    help="accumulate the per-feature firing sketch on the "
                    "drainer (docs/observability.md §10): per-lane firing "
                    "counts / magnitude histograms, flushed to "
                    "feature_stats.serveNNNN.npz at scrape/drain boundaries")
    ap.add_argument("--feature-baseline", default=None, metavar="NPZ",
                    help="training-baseline feature_stats snapshot to drift-"
                    "check each flushed serve window against (implies "
                    "--feature-stats)")
    ap.add_argument("--feature-flush-s", type=float, default=30.0,
                    help="min seconds between firing-sketch flushes")
    ap.add_argument("--drift-warn", type=float, default=0.25,
                    help="PSI drift score that trips a feature_drift warn")
    ap.add_argument("--drift-abort", type=float, default=1.0,
                    help="PSI drift score that drains this replica "
                    "(exit 1) — the serve-side abort tier")
    ap.add_argument("--verbose", action="store_true")
    args = ap.parse_args(argv)

    from sparse_coding__tpu.telemetry import RunTelemetry
    from sparse_coding__tpu.train import preemption
    from sparse_coding__tpu.utils.faults import fault_point

    telemetry = RunTelemetry(
        out_dir=args.events, run_name="serve",
        tags={"replica": args.replica_id} if args.replica_id else None,
    )
    registry = DictRegistry(telemetry=telemetry)
    for exp in args.exports:
        ids = registry.load_export(exp, weights=args.weights)
        print(f"[serve] loaded {len(ids)} dict(s) from {exp}: {ids}")
    if args.subject:
        try:
            subj = attach_subject_from_spec(registry, args.subject)
            print(f"[serve] attached subject {args.subject!r} "
                  f"(width {subj.activation_size})")
        except (ValueError, IndexError) as e:
            ap.error(f"bad --subject spec {args.subject!r}: {e}")
    telemetry.run_start(config={
        "exports": list(args.exports), "weights": args.weights,
        "max_batch": args.max_batch, "max_wait_ms": args.max_wait_ms,
        "dicts": registry.ids(), "replica_id": args.replica_id,
        "dict_generation": args.dict_generation,
        "subjects": registry.subjects(),
    })

    from sparse_coding__tpu.telemetry.anomaly import AnomalyPolicy

    feature_stats_on = bool(args.feature_stats or args.feature_baseline)
    srv = ServeServer(
        registry, host=args.host, port=args.port, telemetry=telemetry,
        max_batch=args.max_batch, max_wait_ms=args.max_wait_ms,
        verbose=args.verbose, dict_generation=args.dict_generation,
        replica_id=args.replica_id,
        feature_stats=feature_stats_on or None,
        feature_baseline=args.feature_baseline,
        feature_flush_s=args.feature_flush_s,
        drift_policy=AnomalyPolicy(
            drift_warn=args.drift_warn, drift_abort=args.drift_abort,
        ) if feature_stats_on else None,
    )
    srv.engine.start()
    if not args.no_warmup:
        n = srv.engine.warmup(topk_ks=args.warmup_topk or ())
        if registry.subjects():
            n += srv.engine.warmup_features(
                args.subject_seq_len, topk_ks=args.warmup_topk or ()
            )
        print(f"[serve] warmed {n} compiled step(s)")
    srv.start()
    if args.port_file:
        Path(args.port_file).write_text(str(srv.port))
    print(f"[serve] listening on {srv.address} "
          f"({len(registry)} dict(s), max_batch {args.max_batch})", flush=True)

    # SIGTERM drain: the PR-5 preemption flag, polled here instead of at a
    # chunk boundary — serving's "boundary" is every loop tick
    preemption.install_signal_handlers()
    preemption.poller_started()
    status = "ok"
    try:
        tick = 0
        while not preemption.preemption_requested():
            # replica-death chaos site: `SC_FAULT=kill:serve_loop:tick=N`
            # SIGKILLs this replica mid-flight, deterministically
            fault_point("serve_loop", tick=tick)
            tick += 1
            # firing-sketch flush cadence (interval-gated internally); an
            # abort-tier train↔serve drift drains this replica — serving a
            # distribution the dict never trained on is not a warning
            srv.maybe_flush_features()
            if srv.drift_abort_requested:
                print("[serve] feature drift past abort threshold — "
                      "draining replica", flush=True)
                srv.drain()
                telemetry.event("serve_drained", reason="feature_drift",
                                requests=srv.engine.stats["requests"])
                srv.close()
                status = "drift_abort"
                return 1
            time.sleep(0.05)
        sig = preemption.preemption_signal()
        print(f"[serve] drain requested (signal {sig}) — rejecting new "
              "requests, completing in-flight", flush=True)
        srv.drain()
        telemetry.event("serve_drained", signum=sig,
                        requests=srv.engine.stats["requests"])
        srv.close()
        status = "drained"
        print("[serve] drained clean — exit 0", flush=True)
        return 0
    except KeyboardInterrupt:
        srv.drain()
        srv.close()
        status = "drained"
        return 0
    finally:
        preemption.poller_stopped()
        telemetry.close(status=status)


if __name__ == "__main__":
    sys.exit(main())
