"""CLI shim: ``python -m sparse_coding__tpu.features <run_dir>``.

The dictionary feature surface: lists top-firing / dead / top-drifting
features from the ``feature_stats.<gen>.npz`` snapshots a run leaves
behind, with ``--json`` for machines, ``--diff GEN_A GEN_B`` to compare two
specific snapshot generations, and ``--threshold X`` as the CI gate (exit
**1** when the train↔serve drift score reaches X; exit **3** when the run
dir holds no snapshots at all). Implementation:
`sparse_coding__tpu.telemetry.feature_stats` (docs/observability.md §10).
"""

from sparse_coding__tpu.telemetry.feature_stats import (
    FeatureSnapshot,
    drift_report,
    load_run_snapshots,
    main,
    render_features,
    summarize_run,
)

__all__ = [
    "FeatureSnapshot",
    "drift_report",
    "load_run_snapshots",
    "main",
    "render_features",
    "summarize_run",
]

if __name__ == "__main__":
    raise SystemExit(main())
