"""sparse_coding__tpu: TPU-native sparse-coding / sparse-autoencoder framework.

A ground-up JAX/XLA rebuild of the capabilities of the reference
`johnathan217/sparse_coding_` codebase (training ensembles of sparse
autoencoders and other dictionary-learning methods on LM activations), designed
TPU-first: stacked-ensemble vmap training under one jit, `jax.sharding` meshes
for scale-out, Pallas kernels for the hot inner loops, and orbax checkpoints.

Layout:
  - `ensemble`   — stacked-ensemble runtime (vmap(grad) + optax under jit)
  - `models`     — dictionary model zoo (SAE family, top-k, FISTA, LISTA, ...)
  - `data`       — synthetic generators, activation chunk store, LM harvesting
  - `lm`         — hook-capable JAX transformer (subject models)
  - `parallel`   — device-mesh sharding of the ensemble/data/dict axes
  - `train`      — sweep orchestrator, train loops, checkpointing
  - `metrics`    — FVU / MMCS / sparsity / moments / perplexity metrics
  - `interp`     — automated-interpretability pipeline
  - `telemetry`  — run events, training-health pack, anomaly guard, transfer
                   audit, `python -m sparse_coding__tpu.report` summaries
"""

from sparse_coding__tpu.ensemble import (
    DictSignature,
    Ensemble,
    EnsembleState,
    build_ensemble,
    make_ensemble_step,
    optim_str_to_func,
    stack_pytrees,
    unstack_pytree,
)

__version__ = "0.1.0"
