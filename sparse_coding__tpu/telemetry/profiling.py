"""Performance attribution: XLA cost/roofline capture, HBM watermarks,
triggered trace windows.

PR 2's telemetry says *what happened* in a run; this module says *where the
time and memory go*, against hardware peaks — the roofline discipline every
perf PR needs to prove which entry point it moved:

  - **Cost capture** (`jit_cost_fields` / `compiled_cost_fields`): analytic
    FLOPs + HBM bytes from XLA's ``cost_analysis()`` and argument/output/
    temp footprints from ``memory_analysis()``. `tracked_jit` calls
    `jit_cost_fields` on every compile it detects, so named ``compile``
    events in events.jsonl carry a ``cost`` block for free. The default
    capture re-lowers through jax's lowering cache and reads the HLO cost
    analysis WITHOUT a backend compile (~tens of ms); the memory footprints
    require compiling a second executable, so they are captured only on
    demand (``memory=True`` — `Ensemble.compiled_cost`, bench setup) or
    with ``SC_COST_CAPTURE=full``, and that extra compile is masked from
    the `jax.monitoring` compile counters so it cannot pollute the
    compile-state signal bench.py reports. Everything here is
    backend-best-effort: any field XLA does not expose is simply absent,
    and a failed capture never fails the run.
  - **Roofline attribution** (`roofline_summary`): combines captured
    FLOPs/bytes with `utils.bench_common`'s per-chip peaks
    (``peak_tflops`` / ``hbm_gbps``) to classify an entry point compute- vs
    bandwidth-bound and, given a measured wall time, report the
    achieved-vs-attainable fraction.
  - **HBM watermarks** (`record_hbm_watermarks` / `hbm_watermarks`): samples
    ``device.memory_stats()`` (bytes_in_use / peak_bytes_in_use /
    bytes_limit) into RunTelemetry gauges. A host-side C call — no device
    computation is fenced and no jax.Array is materialized, so sampling at
    flush boundaries preserves the zero-per-step-host-transfer invariant
    `transfer_audit()` enforces. CPU returns None; gauges are then absent
    (deterministically — tests rely on it).
  - **Triggered traces** (`TraceTrigger`): arms `utils.trace`'s profiler
    window programmatically — by step window (env ``SC_TRACE_WINDOW=N:M`` +
    ``SC_TRACE_DIR``, or constructor args), or by the `AnomalyGuard` on
    first anomaly — and writes the trace dir path into the event log and
    the diagnostic bundle.
"""

from __future__ import annotations

import threading
import warnings
from pathlib import Path
from typing import Any, Dict, Optional

from sparse_coding__tpu.utils import flags

__all__ = [
    "compiled_cost_fields",
    "jit_cost_fields",
    "monitoring_suppressed",
    "roofline_summary",
    "device_memory_stats",
    "hbm_watermarks",
    "record_hbm_watermarks",
    "TraceTrigger",
]

# capture depth for the per-compile cost capture: "0"/"false"/"no" disables
# it entirely, "full" additionally compiles a throwaway executable for the
# memory_analysis footprints (masked from the monitoring counters), anything
# else (the default) reads the HLO cost analysis only — no backend compile
COST_CAPTURE_ENV = flags.SC_COST_CAPTURE.name


def _capture_mode() -> str:
    v = flags.SC_COST_CAPTURE.get().lower()
    if v in ("0", "false", "no", "off"):
        return "off"
    if v in ("full", "2", "memory"):
        return "full"
    return "cost"


# while a cost capture compiles its throwaway executable, the jax.monitoring
# bridge (events._install_jax_listeners) must not count it — the
# compile.backend.* counters exist to expose the RUN's compile state, and
# profiling overhead polluting them would corrupt bench.py's
# sessions-differ-by-compile-state signal
_SUPPRESS = threading.local()


def monitoring_suppressed() -> bool:
    return getattr(_SUPPRESS, "depth", 0) > 0


# -- XLA cost / memory capture ------------------------------------------------

def compiled_cost_fields(compiled) -> Optional[Dict[str, Any]]:
    """Extract analytic cost + memory fields from a `jax.stages.Compiled`.

    Returns a flat dict (all best-effort; absent keys mean the backend does
    not report them):

      ``flops``            analytic FLOPs of one dispatch
      ``bytes_accessed``   HBM bytes touched per dispatch (XLA's estimate)
      ``argument_bytes`` / ``output_bytes`` / ``temp_bytes`` /
      ``alias_bytes`` / ``generated_code_bytes``   memory_analysis footprints
      ``peak_bytes``       backend peak when exposed, else the
                           argument+output+temp sum (an upper-ish proxy,
                           flagged by ``peak_bytes_estimated``)

    None when neither analysis yields anything (e.g. a backend that returns
    empty cost analyses).
    """
    out: Dict[str, Any] = {}
    try:
        ca = compiled.cost_analysis()
        # jax returns a dict on some versions, a one-element list of dicts on
        # others (one per device program)
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else None
        if ca:
            for src, dst in (("flops", "flops"), ("bytes accessed", "bytes_accessed")):
                v = ca.get(src)
                if v is not None and float(v) >= 0:
                    out[dst] = float(v)
    except Exception:
        pass
    try:
        ma = compiled.memory_analysis()
        if ma is not None:
            for src, dst in (
                ("argument_size_in_bytes", "argument_bytes"),
                ("output_size_in_bytes", "output_bytes"),
                ("temp_size_in_bytes", "temp_bytes"),
                ("alias_size_in_bytes", "alias_bytes"),
                ("generated_code_size_in_bytes", "generated_code_bytes"),
            ):
                v = getattr(ma, src, None)
                if v is not None:
                    out[dst] = int(v)
            peak = getattr(ma, "peak_memory_in_bytes", None)
            if peak is not None:
                out["peak_bytes"] = int(peak)
            elif {"argument_bytes", "output_bytes", "temp_bytes"} <= out.keys():
                out["peak_bytes"] = (
                    out["argument_bytes"] + out["output_bytes"] + out["temp_bytes"]
                )
                out["peak_bytes_estimated"] = True
    except Exception:
        pass
    return out or None


def _lowered_cost_fields(lowered) -> Dict[str, Any]:
    """flops / bytes_accessed from a `jax.stages.Lowered`'s HLO cost
    analysis — no backend compile happens (verified: zero
    ``backend_compile_duration`` monitoring events), and the numbers match
    the compiled executable's analysis.

    UNIT CAVEAT (applies to XLA's cost analysis in both forms): while/scan
    loop bodies are counted ONCE — trip counts are not folded in. For a
    ``step_scan``-style program the cost block therefore describes ONE
    fused step, not the whole K-step dispatch (verified: the bench scan-128
    program reports exactly the analytic single-step FLOPs). Arithmetic
    intensity and the roofline bound are unaffected (flops and bytes share
    the unit); anything comparing against wall time must scale the time to
    the same unit — see bench.py's ``units_per_cost``."""
    out: Dict[str, Any] = {}
    try:
        ca = lowered.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else None
        if ca:
            for src, dst in (("flops", "flops"), ("bytes accessed", "bytes_accessed")):
                v = ca.get(src)
                if v is not None and float(v) >= 0:
                    out[dst] = float(v)
    except Exception:
        pass
    return out


def jit_cost_fields(fn, args=(), kwargs=None, memory: Optional[bool] = None) -> Optional[Dict[str, Any]]:
    """Cost fields for a jitted callable at a concrete call signature.

    ``fn.lower(*args, **kwargs)`` immediately after the real call hits jax's
    lowering caches (donated buffers are fine — lowering only needs avals),
    and the Lowered's HLO ``cost_analysis()`` yields flops/bytes WITHOUT a
    backend compile. ``memory=True`` (or ``SC_COST_CAPTURE=full``)
    additionally compiles a throwaway executable for the
    ``memory_analysis()`` footprints — a real second XLA compile, so it is
    reserved for setup-time callers (`Ensemble.compiled_cost`, bench preps)
    and masked from the `jax.monitoring` compile counters while it runs.
    Returns None (never raises) when the callable has no ``lower``, the
    signature cannot be re-lowered, or capture is disabled via
    ``SC_COST_CAPTURE=0``.
    """
    mode = _capture_mode()
    if mode == "off" or not hasattr(fn, "lower"):
        return None
    if memory is None:
        memory = mode == "full"
    try:
        lowered = fn.lower(*args, **(kwargs or {}))
        out = _lowered_cost_fields(lowered)
        if memory:
            _SUPPRESS.depth = getattr(_SUPPRESS, "depth", 0) + 1
            try:
                full = compiled_cost_fields(lowered.compile())
            finally:
                _SUPPRESS.depth -= 1
            if full:
                out.update(full)  # post-optimization analyses win
        return out or None
    except Exception:
        return None


# -- roofline attribution -----------------------------------------------------

def roofline_summary(
    flops: float,
    bytes_accessed: float,
    device_kind: str,
    seconds: Optional[float] = None,
) -> Dict[str, Any]:
    """Classify one program against its chip's roofline.

    ``flops`` / ``bytes_accessed`` are per dispatch (XLA cost analysis or
    analytic); ``device_kind`` selects the peak table
    (`utils.bench_common.peak_tflops` / `hbm_gbps`); ``seconds`` (optional)
    is the measured wall time of one dispatch.

    Returns::

        {"arithmetic_intensity": flops/byte,
         "ridge_intensity":      peak_flops / peak_bw (the roofline knee),
         "bound":                "compute" | "bandwidth",
         "peak_tflops": ..., "hbm_gbps": ...,
         "attainable_tflops":    min(peak, intensity * bw),
         # with `seconds`:
         "achieved_tflops":      flops / seconds / 1e12,
         "achieved_fraction":    achieved / attainable,
         "achieved_gbps":        bytes / seconds / 1e9}
    """
    from sparse_coding__tpu.utils.bench_common import hbm_gbps, peak_tflops

    peak = peak_tflops(device_kind)
    bw = hbm_gbps(device_kind)
    intensity = flops / bytes_accessed if bytes_accessed > 0 else float("inf")
    ridge = peak * 1e12 / (bw * 1e9)  # FLOPs per byte at the knee
    attainable = min(peak, intensity * bw * 1e9 / 1e12)
    out: Dict[str, Any] = {
        "flops": float(flops),
        "bytes_accessed": float(bytes_accessed),
        "arithmetic_intensity": round(intensity, 3),
        "ridge_intensity": round(ridge, 3),
        "bound": "compute" if intensity >= ridge else "bandwidth",
        "peak_tflops": peak,
        "hbm_gbps": bw,
        "attainable_tflops": round(attainable, 3),
    }
    if seconds is not None and seconds > 0:
        achieved = flops / seconds / 1e12
        out["achieved_tflops"] = round(achieved, 4)
        out["achieved_fraction"] = round(achieved / attainable, 4) if attainable > 0 else None
        out["achieved_gbps"] = round(bytes_accessed / seconds / 1e9, 2)
    return out


# -- HBM watermarks -----------------------------------------------------------

_WATERMARK_KEYS = ("bytes_in_use", "peak_bytes_in_use", "bytes_limit")


def device_memory_stats(device) -> Optional[Dict[str, int]]:
    """``device.memory_stats()`` filtered to the watermark fields; None when
    the backend does not report (CPU) or the call fails."""
    try:
        stats = device.memory_stats()
    except Exception:
        return None
    if not stats:
        return None
    return {k: int(stats[k]) for k in _WATERMARK_KEYS if k in stats}


def hbm_watermarks(devices=None) -> Dict[str, Dict[str, int]]:
    """Per-device watermark dict ``{"d0": {"bytes_in_use": ..., ...}, ...}``
    for every local device that reports memory stats (possibly empty).

    Multi-host runs key by ``p<proc>.d<global_id>`` instead of the local
    enumeration index: per-process event logs merge into one report, and
    two hosts' local ``d0`` gauges must not collide there (ISSUE 4).
    Single-host keys stay ``d<i>`` — layout stability."""
    if devices is None:
        try:
            import jax

            devices = jax.local_devices()
        except Exception:
            return {}
    from sparse_coding__tpu.telemetry.multihost import process_info

    pidx, pcount = process_info()
    out: Dict[str, Dict[str, int]] = {}
    for i, d in enumerate(devices):
        stats = device_memory_stats(d)
        if stats:
            key = f"p{pidx}.d{getattr(d, 'id', i)}" if pcount > 1 else f"d{i}"
            out[key] = stats
    return out


def record_hbm_watermarks(telemetry, devices=None) -> Dict[str, Dict[str, int]]:
    """Sample HBM watermarks into `telemetry` gauges (``hbm.d<i>.<field>``;
    ``hbm.p<i>.d<j>.<field>`` on multi-host runs — merge-safe).

    A flush-boundary act: reading memory_stats is a host-side query — it
    fences nothing and materializes no jax.Array, so it is legal inside
    `transfer_audit` regions and adds zero per-step host transfers. Gauges
    reach events.jsonl via the next ``snapshot`` (run_end emits one).
    Returns the sample (empty on backends without memory stats)."""
    marks = hbm_watermarks(devices)
    if telemetry is not None:
        for dev, stats in marks.items():
            for field, v in stats.items():
                telemetry.gauge_set(f"hbm.{dev}.{field}", float(v))
    return marks


# -- triggered trace capture --------------------------------------------------

class TraceTrigger:
    """Programmatic arming of `utils.trace` profiler windows.

    Two arming paths, both driving the same reentrancy-safe
    `start_trace_safe` / `stop_trace_safe` pair (a trigger firing inside a
    manual ``trace(...)`` block degrades to a warning, never an exception):

      - **step window**: ``TraceTrigger(..., start_step=N, stop_step=M)`` —
        drivers call ``on_step(global_step)`` at flush/chunk boundaries; the
        capture starts at the first boundary at or past N and stops at the
        first boundary at or past M (when one boundary jump crosses the
        whole window — chunk-granularity drivers — one boundary-to-boundary
        window is captured rather than nothing). Written into
        ``<out_dir>/trace_step<N>``. ``TraceTrigger.from_env(...)`` reads
        ``SC_TRACE_WINDOW="N:M"`` (and optional ``SC_TRACE_DIR``) so any
        driver run can be traced without a code change.
      - **anomaly**: `AnomalyGuard` calls ``fire(reason=...)`` on first
        anomaly; the trigger starts a trace immediately and stops it after
        ``anomaly_windows`` further ``on_step`` calls — capturing the steps
        right after the blowup. One anomaly capture per run (the first).

    Every capture emits a ``trace`` event (``{"dir", "reason",
    "start_step", "stop_step"}``) to the telemetry, and `last_trace_dir`
    exposes the most recent dir for diagnostic bundles.
    """

    def __init__(
        self,
        telemetry=None,
        out_dir: Optional[str] = None,
        start_step: Optional[int] = None,
        stop_step: Optional[int] = None,
        on_anomaly: bool = True,
        anomaly_windows: int = 1,
        trace_dir: Optional[str] = None,
    ):
        self.telemetry = telemetry
        self.out_dir = Path(out_dir) if out_dir is not None else None
        self.start_step = start_step
        self.stop_step = stop_step
        self.on_anomaly = bool(on_anomaly)
        self.anomaly_windows = max(1, int(anomaly_windows))
        self._trace_dir_override = trace_dir
        self._active: Optional[str] = None       # dir of the window WE started
        self._active_reason: Optional[str] = None
        self._active_start_step: Optional[int] = None
        self._window_done = False                # step window fires once
        self._anomaly_fired = False              # first anomaly only
        self._stop_after: Optional[int] = None   # countdown of on_step calls
        self.last_trace_dir: Optional[str] = None

    @classmethod
    def from_env(cls, telemetry=None, out_dir: Optional[str] = None, env=None, **kw):
        """Build from ``SC_TRACE_WINDOW="N:M"`` / ``SC_TRACE_DIR`` (anomaly
        arming stays on by default). Malformed values warn and are ignored."""
        window = flags.SC_TRACE_WINDOW.get(env)
        start = stop = None
        if window:
            try:
                lo, _, hi = window.partition(":")
                start, stop = int(lo), int(hi)
            except ValueError:
                warnings.warn(
                    f"ignoring malformed SC_TRACE_WINDOW={window!r} "
                    "(expected 'start:stop' in steps)",
                    RuntimeWarning,
                )
                start = stop = None
        return cls(
            telemetry=telemetry,
            out_dir=out_dir,
            start_step=start,
            stop_step=stop,
            trace_dir=flags.SC_TRACE_DIR.get(env),
            **kw,
        )

    # -- plumbing ------------------------------------------------------------

    def _dir_for(self, tag: str) -> str:
        if self._trace_dir_override:
            return self._trace_dir_override
        base = self.out_dir if self.out_dir is not None else Path("/tmp/jax-trace")
        return str(base / f"trace_{tag}")

    def _start(self, log_dir: str, reason: str, step: Optional[int]) -> Optional[str]:
        from sparse_coding__tpu.utils.trace import start_trace_safe

        if not start_trace_safe(log_dir):
            return None
        self._active = log_dir
        self._active_reason = reason
        self._active_start_step = step
        return log_dir

    def _stop(self, step: Optional[int] = None):
        from sparse_coding__tpu.utils.trace import stop_trace_safe

        if self._active is None:
            return
        stop_trace_safe()
        self.last_trace_dir = self._active
        if self.telemetry is not None:
            self.telemetry.event(
                "trace",
                dir=self._active,
                reason=self._active_reason,
                start_step=self._active_start_step,
                stop_step=step,
            )
            self.telemetry.counter_inc("trace.captures")
        self._active = None
        self._active_reason = None
        self._stop_after = None

    # -- public surface ------------------------------------------------------

    @property
    def active(self) -> bool:
        return self._active is not None

    def on_step(self, step: int):
        """Drive the trigger from a flush/chunk boundary: `step` is the
        cumulative train-step count. Host-side integer compares only."""
        step = int(step)
        if self._active is not None:
            if self._stop_after is not None:
                self._stop_after -= 1
                if self._stop_after <= 0:
                    self._stop(step)
            elif self.stop_step is not None and step >= self.stop_step:
                self._stop(step)
            return
        if (
            not self._window_done
            and self.start_step is not None
            and self.stop_step is not None
            and step >= self.start_step
        ):
            self._window_done = True
            started = self._start(self._dir_for(f"step{step}"), "step_window", step)
            if started is not None and step >= self.stop_step:
                # the caller steps the trigger at boundaries coarser than
                # the requested window (chunk-granularity drivers): capture
                # ONE boundary-to-boundary window starting here instead of
                # silently skipping the request
                self._stop_after = 1

    def fire(self, reason: str = "anomaly", step: Optional[int] = None) -> Optional[str]:
        """Anomaly-path arming (AnomalyGuard): start a capture NOW, stopping
        after `anomaly_windows` further `on_step` calls. Returns the trace
        dir when a capture started (first anomaly, profiler free), else
        None."""
        if not self.on_anomaly or self._anomaly_fired or self._active is not None:
            return None
        tag = f"anomaly_step{step}" if step is not None else "anomaly"
        started = self._start(self._dir_for(tag), reason, step)
        if started is not None:
            # consume the run's single anomaly capture only on an actual
            # start — a foreign trace refusing the profiler must leave the
            # attempt available for the next anomaly
            self._anomaly_fired = True
            self._stop_after = self.anomaly_windows
        return started

    def close(self, step: Optional[int] = None):
        """Stop any in-flight capture (drivers call this in their finally)."""
        self._stop(step)

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        self.close()
        return False
