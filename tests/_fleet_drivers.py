"""Importable `import:` payload drivers for fleet tests (see
`fleet.worker.run_item`): a slow driver that honors the preemption flag at
its poll boundary like a real training loop, and a quick driver that leaves
a verifiable learned-dict export."""

import time
from pathlib import Path

from sparse_coding__tpu.train import preemption


def slow_driver(output_folder, resume=None, seconds=30.0, poll=0.05):
    """Spin until `seconds` elapse, polling the preemption flag the way a
    real driver polls at chunk boundaries."""
    deadline = time.time() + seconds
    while time.time() < deadline:
        if preemption.preemption_requested():
            raise preemption.Preempted("preempted at poll boundary")
        time.sleep(poll)
    return []


def quick_driver(output_folder, resume=None):
    """Instantly 'train': write an export the manifest can verify."""
    out = Path(output_folder) / "epoch_0"
    out.mkdir(parents=True, exist_ok=True)
    (out / "learned_dicts.pkl").write_bytes(b"quick-dict-bytes")
    return []


def interrupt_driver(output_folder, resume=None):
    """Simulate an operator Ctrl-C landing inside the driver."""
    raise KeyboardInterrupt
