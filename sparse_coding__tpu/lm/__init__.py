from sparse_coding__tpu.lm.model import (
    LMConfig,
    config_for,
    dense_attention,
    forward,
    get_activation_size,
    init_params,
    lm_loss,
    make_tensor_name,
    run_with_cache,
    run_with_hooks,
)
from sparse_coding__tpu.lm.convert import config_from_hf, load_model, params_from_hf
from sparse_coding__tpu.lm.ring_attention import (
    make_sequence_parallel_fn,
    ring_attention,
    sequence_parallel_forward,
    ulysses_attention,
)
