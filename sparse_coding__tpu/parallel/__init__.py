from sparse_coding__tpu.parallel.mesh import (
    DATA_AXIS,
    DICT_AXIS,
    MODEL_AXIS,
    batch_sharding,
    default_mesh_shape,
    infer_state_specs,
    make_mesh,
    per_model_batch_sharding,
    shard_state,
)
from sparse_coding__tpu.parallel.distributed import (
    host_local_to_global,
    initialize_distributed,
    local_batch_slice,
)
