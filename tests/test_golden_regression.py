"""Golden trained-dict regression gate (VERDICT r4 next #7).

`tests/golden/cfg2_smoke/` holds committed trained dictionaries + expected
metrics (the reference's `output_basic_test/` pattern), generated once by
`scripts/make_golden_fixture.py`. Two gates:

  1. re-evaluate the COMMITTED dicts on regenerated (seeded) data — catches
     metric/eval/data-generator drift at tight tolerance;
  2. RETRAIN the fixture from scratch and compare to golden — catches
     behavioral drift in init / loss / optimizer / the training step at
     loose tolerance, plus dictionary-level agreement (MMCS to committed).

Per-round artifact JSONs record history; this is the piece CI re-verifies.
"""

import json
import sys
from pathlib import Path

import numpy as np
import pytest

REPO = Path(__file__).resolve().parent.parent
GOLDEN = REPO / "tests" / "golden" / "cfg2_smoke"

sys.path.insert(0, str(REPO / "scripts"))


@pytest.fixture(scope="module")
def golden():
    return json.loads((GOLDEN / "golden.json").read_text())


@pytest.fixture(scope="module")
def committed_dicts():
    from sparse_coding__tpu.train.checkpoint import load_learned_dicts

    return load_learned_dicts(GOLDEN / "learned_dicts.pkl")


def test_committed_dicts_reevaluate_to_golden(golden, committed_dicts):
    # THE fixture's own generator constructor — hand-copied kwargs here
    # would silently drift from the stream the golden numbers pin
    from make_golden_fixture import STEPS_PER_EPOCH, make_generator

    from sparse_coding__tpu import metrics as sm

    gen = make_generator()
    for _ in range(STEPS_PER_EPOCH):
        next(gen)  # identical stream position to the generator script
    eval_batch = next(gen)
    truth = np.asarray(gen.feats)

    tol = golden["tolerances"]
    dicts = [ld for ld, _hp in committed_dicts]
    rows = sm.evaluate_dicts(dicts, eval_batch)
    for member, ld, row in zip(golden["members"], dicts, rows):
        assert float(row["fvu"]) == pytest.approx(
            member["fvu"], rel=tol["reeval_fvu_rtol"], abs=1e-4
        ), member
        assert float(row["l0"]) == pytest.approx(
            member["l0"], rel=tol["reeval_l0_rtol"]
        ), member
        assert float(sm.mmcs(ld, truth)) == pytest.approx(
            member["mmcs_to_truth"], rel=0.05
        ), member


@pytest.mark.slow
def test_retrain_matches_golden(golden, committed_dicts):
    from make_golden_fixture import fixture_metrics, train_fixture_ensemble

    from sparse_coding__tpu import metrics as sm

    ens, eval_batch, truth, traj = train_fixture_ensemble()
    retrained = ens.to_learned_dicts()
    metrics = fixture_metrics(retrained, eval_batch, truth)

    tol = golden["tolerances"]
    for member, got in zip(golden["members"], metrics):
        assert got["fvu"] == pytest.approx(
            member["fvu"], rel=tol["retrain_fvu_rtol"], abs=5e-3
        ), (member, got)
        assert got["l0"] == pytest.approx(
            member["l0"], rel=tol["retrain_l0_rtol"]
        ), (member, got)
    # dictionary-level agreement with the committed fixture (not just
    # aggregate metrics): same seeds + deterministic CPU training should
    # land on essentially the same features
    for (committed, _hp), new, member in zip(
        committed_dicts, retrained, golden["members"]
    ):
        m = float(sm.mmcs(new, committed))
        assert m >= tol["retrain_mmcs_to_committed_min"], (member, m)
