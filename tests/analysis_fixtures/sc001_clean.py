"""Fixture: SC001 clean twin — jnp.issubdtype, plus the legitimate
integer-kind wire idiom SC001 must not flag."""

import jax.numpy as jnp


def keep_resident(x):
    if jnp.issubdtype(x.dtype, jnp.floating):
        return x.astype(jnp.bfloat16)
    return x


def is_raw_codec(x):
    return x.dtype.kind in ("i", "u", "V")
