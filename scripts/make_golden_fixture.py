"""Golden trained-dict fixture generator (VERDICT r4 next #7).

Trains the smoke-scale BASELINE-config-2 shape (tied-SAE l1-sweep ensemble on
synthetic data with a PLANTED ground-truth dictionary) to its FVU plateau,
then commits the exported dicts + expected metrics to `tests/golden/` —
the cross-round regression anchor the reference keeps as
`output_basic_test/` (committed sweep outputs + `filename_explanations.txt`).
Per-round JSON artifacts record history; THIS is re-verified by CI:
`tests/test_golden_regression.py` (a) re-evaluates the committed dicts and
(b) retrains from scratch and compares, so a behavioral change in init /
loss / optimizer / training loop fails the suite instead of silently
shifting the next round's artifacts.

Everything is seeded and CPU-deterministic; tolerances in golden.json absorb
XLA-version numeric drift.

Run: `python scripts/make_golden_fixture.py` (CPU, ~1 min) — only when a
deliberate behavioral change requires re-pinning; commit the diff it prints.
"""

from __future__ import annotations

import json
import os
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

GOLDEN_DIR = REPO / "tests" / "golden" / "cfg2_smoke"

# smoke-scale config-2 shape: tied SAEs, 4x overcomplete, 3-point l1 grid
D_ACT = 64
N_DICT = 256
# 1e-4: dense near-autoencoding; 1e-3: the feature-recovery point (MMCS to
# planted truth ~0.6 at plateau); 3e-3: sparse-but-alive. A 1e-2 member
# collapses at this scale — a dead dict is a weak regression anchor.
L1_GRID = (1e-4, 1e-3, 3e-3)
BATCH = 512
STEPS_PER_EPOCH = 64
MAX_EPOCHS = 40
PLATEAU_TOL = 0.002
SEED = 0


def make_generator():
    """THE seeded data generator the golden numbers are pinned on — the
    regression test must rebuild the identical stream, so the constructor
    lives here and only here."""
    import jax

    from sparse_coding__tpu.data import RandomDatasetGenerator

    return RandomDatasetGenerator(
        activation_dim=D_ACT,
        n_ground_truth_components=2 * D_ACT,
        batch_size=BATCH,
        feature_num_nonzero=6,
        feature_prob_decay=0.99,
        correlated=False,
        key=jax.random.PRNGKey(SEED + 1000),
    )


def train_fixture_ensemble():
    """The exact training run the golden numbers pin. Deterministic on CPU:
    fixed seeds, fixed batch order, fp32 everywhere. Returns (ensemble,
    eval_batch, ground_truth, fvu_trajectory)."""
    import jax

    from sparse_coding__tpu import build_ensemble, metrics as sm
    from sparse_coding__tpu.models import FunctionalTiedSAE

    gen = make_generator()
    # one fixed epoch of data, reused every epoch (plateau needs repetition)
    chunks = [next(gen) for _ in range(STEPS_PER_EPOCH)]
    eval_batch = next(gen)

    ens = build_ensemble(
        FunctionalTiedSAE,
        jax.random.PRNGKey(SEED),
        [{"l1_alpha": a} for a in L1_GRID],
        optimizer_kwargs={"learning_rate": 1e-3},
        activation_size=D_ACT,
        n_dict_components=N_DICT,
    )
    traj = []
    prev, stall = None, 0
    for epoch in range(MAX_EPOCHS):
        for b in chunks:
            ens.step_batch(b)
        fvus = [r["fvu"] for r in sm.evaluate_dicts(ens.to_learned_dicts(), eval_batch)]
        cur = float(sum(fvus) / len(fvus))
        traj.append(round(cur, 5))
        if prev is not None and (prev - cur) < PLATEAU_TOL * prev:
            stall += 1
            if stall >= 2:
                break
        elif prev is not None:
            stall = 0
        prev = cur
    return ens, eval_batch, gen.feats, traj


def fixture_metrics(dicts, eval_batch, ground_truth):
    import numpy as np

    from sparse_coding__tpu import metrics as sm

    rows = sm.evaluate_dicts(dicts, eval_batch)
    return [
        {
            "l1_alpha": a,
            "fvu": round(float(r["fvu"]), 5),
            "l0": round(float(r["l0"]), 2),
            "mmcs_to_truth": round(float(sm.mmcs(ld, np.asarray(ground_truth))), 4),
        }
        for a, ld, r in zip(L1_GRID, dicts, rows)
    ]


POD_RUN_DIR = REPO / "tests" / "golden" / "pod_run"
POD_BASE_TS = 1_754_200_000.0  # fixed: the fixture must regenerate identically


def make_pod_run_fixture():
    """Deterministic two-process pod run directory (ISSUE 4 satellite).

    Hand-stamped event logs — NOT a real training run: real runs stamp wall
    clocks, and a golden fixture must be byte-stable. The shape mirrors what
    `telemetry.multihost`-wired drivers write on a two-host pod: per-process
    `events.p<i>.jsonl`, every record tagged `process_index`, heartbeats
    with allgathered window times + clock offsets, `skew.flush.*` gauges,
    `hbm.p<i>.d<j>.*` watermarks, and a straggling host (p1 is ~1 s slower
    on chunk 1). `tests/test_monitor.py` runs `monitor --once` and the
    report against this directory in tier-1.
    """
    POD_RUN_DIR.mkdir(parents=True, exist_ok=True)
    chunk_secs = {0: (1.00, 1.05), 1: (1.10, 2.15), 2: (1.02, 1.08)}
    for p in (0, 1):
        fp = {
            "python": "3.11.8", "jax": "0.6.0", "jaxlib": "0.6.0",
            "backend": "cpu", "device_kind": "golden-cpu", "device_count": 8,
            "process_index": p, "process_count": 2, "git_sha": "g0lden",
        }
        seq = 0
        t = POD_BASE_TS

        def rec(event, dt=1.0, **fields):
            nonlocal seq, t
            seq += 1
            t += dt
            return {"seq": seq, "ts": round(t, 3), "event": event,
                    "process_index": p, **fields}

        events = [
            rec("run_start", run_name="pod_golden",
                config={"batch": 4096, "l1_values": [1e-4, 1e-3]},
                fingerprint=fp),
            rec("compile", name="ensemble.step_scan", seconds=2.5 + 0.1 * p),
        ]
        steps = 0
        for chunk in range(3):
            mine, theirs = chunk_secs[chunk][p], chunk_secs[chunk][1 - p]
            steps += 64
            events.append(rec("chunk_start", chunk=chunk))
            events.append(rec("chunk_end", dt=mine, chunk=chunk,
                              seconds=mine, steps=64))
            events.append(rec(
                "heartbeat", dt=0.01, step=steps, steps=steps,
                window_seconds=mine,
                window_seconds_by_process=[chunk_secs[chunk][0], chunk_secs[chunk][1]],
                skew_seconds=round(abs(mine - theirs), 4),
                clock_offset_seconds=0.012 * p,
                clock_uncertainty_seconds=0.004,
            ))
        events.append(rec(
            "snapshot",
            counters={"chunks": 3, "chunk.seconds": round(sum(chunk_secs[c][p] for c in range(3)), 3),
                      "compile.backend.count": 3,
                      "compile.backend.seconds": 2.9,
                      "heartbeats": 3, "train.steps": steps},
            gauges={f"hbm.p{p}.d{4 * p + j}.bytes_in_use": float(2**28 + j)
                    for j in range(2)}
            | {f"hbm.p{p}.d{4 * p + j}.peak_bytes_in_use": float(2**29 + j)
               for j in range(2)}
            | {f"hbm.p{p}.d{4 * p + j}.bytes_limit": float(2**31)
               for j in range(2)}
            | {"skew.flush.max_seconds": 1.08, "skew.flush.min_seconds": 1.02,
               "skew.flush.spread_seconds": 0.06},
        ))
        events.append(rec("run_end", status="ok", steps=steps,
                          steps_per_sec=round(steps / (6.0 + p), 3),
                          wall_seconds=6.0 + p))
        with open(POD_RUN_DIR / f"events.p{p}.jsonl", "w") as f:
            for e in events:
                f.write(json.dumps(e) + "\n")
    print(f"Wrote {POD_RUN_DIR}/events.p0.jsonl + events.p1.jsonl")


RESUMED_RUN_DIR = REPO / "tests" / "golden" / "resumed_run"
RESUMED_BASE_TS = 1_754_300_000.0  # fixed: the fixture must regenerate identically


def make_resumed_run_fixture():
    """Deterministic preempted-and-resumed run directory (ISSUE 5 satellite).

    Hand-stamped event logs — NOT a real training run (real runs stamp wall
    clocks; a golden fixture must be byte-stable). The shape mirrors what a
    supervised `basic_l1_sweep` writes across one preemption: generation 1
    trains chunks 0–1, records a ``preempt`` + ``checkpoint`` event and a
    ``run_end`` with status "preempted"; the supervisor logs the ``restart``
    into ``supervisor_events.jsonl``; generation 2 appends to the SAME
    ``events.jsonl`` with a ``resume`` event and finishes chunk 2.
    `tests/test_monitor.py` renders `monitor --once` and the report's
    "Recovery" section from this directory in tier-1.
    """
    RESUMED_RUN_DIR.mkdir(parents=True, exist_ok=True)
    seq = 0
    t = RESUMED_BASE_TS

    def rec(event, dt=1.0, **fields):
        nonlocal seq, t
        seq += 1
        t += dt
        return {"seq": seq, "ts": round(t, 3), "event": event, **fields}

    ckpt = "out/resumed_golden/ckpt_1"
    cursor = {"chunk": 1, "epoch": 0, "position": 1, "key": [1234, 5678]}
    gen1 = [
        rec("run_start", run_name="resumed_golden", generation=0,
            config={"batch": 512, "l1_values": [1e-4, 1e-3]},
            fingerprint={"python": "3.11.8", "jax": "0.6.0", "backend": "cpu",
                         "device_kind": "golden-cpu", "device_count": 1,
                         "git_sha": "g0lden"}),
        rec("compile", name="ensemble.step_batch", seconds=2.1),
        rec("chunk_start", chunk=0, epoch=0, position=0),
        rec("chunk_end", dt=1.4, chunk=0, epoch=0, position=0, seconds=1.4,
            steps=12),
        rec("chunk_start", chunk=2, epoch=0, position=1),
        rec("chunk_end", dt=1.4, chunk=2, epoch=0, position=1, seconds=1.4,
            steps=12),
        rec("checkpoint", path=ckpt, cursor=1, reason="preempt"),
        rec("preempt", signum=15, checkpoint=ckpt, cursor=1),
        rec("snapshot",
            counters={"chunks": 2, "train.steps": 24, "checkpoints": 1},
            gauges={}),
        rec("run_end", status="preempted", generation=0, steps=24,
            wall_seconds=8.1),
    ]
    # generation 2 APPENDS to the same events.jsonl (seq restarts — each
    # process writes its own monotonic seq, exactly like a real rerun)
    seq = 0
    gen2 = [
        rec("run_start", run_name="resumed_golden", generation=1,
            config={"batch": 512, "l1_values": [1e-4, 1e-3]},
            fingerprint={"python": "3.11.8", "jax": "0.6.0", "backend": "cpu",
                         "device_kind": "golden-cpu", "device_count": 1,
                         "git_sha": "g0lden"}),
        rec("resume", checkpoint=ckpt, cursor=cursor),
        rec("compile", name="ensemble.step_batch", seconds=2.2),
        rec("chunk_start", chunk=1, epoch=0, position=2),
        rec("chunk_end", dt=1.4, chunk=1, epoch=0, position=2, seconds=1.4,
            steps=12),
        rec("snapshot",
            counters={"chunks": 1, "train.steps": 12, "resumes": 1},
            gauges={}),
        rec("run_end", status="ok", generation=1, steps=12, wall_seconds=6.2),
    ]
    with open(RESUMED_RUN_DIR / "events.jsonl", "w") as f:
        for e in gen1 + gen2:
            f.write(json.dumps(e) + "\n")
    seq = 0
    t = RESUMED_BASE_TS
    # spawn/restart records carry the child's run_dir + generation (ISSUE 9
    # satellite) so the goodput merger joins them without path guessing;
    # the basename matches the fixture dir, keeping the join relocatable
    run_dir = "out/resumed_run"
    sup = [
        rec("run_start", run_name="supervisor", generation=0,
            config={"cmd": ["python", "-m", "driver"], "max_restarts": 8,
                    "restart_on": "preempt"}),
        rec("spawn", attempt=0, generation=0, run_dir=run_dir,
            cmd=["python", "-m", "driver"], resume=False),
        rec("restart", dt=9.0, attempt=1, generation=1, run_dir=run_dir,
            exit_code=75, classification="preempt", backoff_seconds=1.0,
            downtime_seconds=1.1),
        rec("spawn", attempt=1, generation=1, run_dir=run_dir,
            cmd=["python", "-m", "driver"], resume=True),
        rec("run_end", dt=7.0, status="ok", run_name="supervisor",
            generation=0, wall_seconds=17.3),
    ]
    with open(RESUMED_RUN_DIR / "supervisor_events.jsonl", "w") as f:
        for e in sup:
            f.write(json.dumps(e) + "\n")
    print(f"Wrote {RESUMED_RUN_DIR}/events.jsonl + supervisor_events.jsonl")


GOODPUT_RUN_DIR = REPO / "tests" / "golden" / "goodput_run"
GOODPUT_BASE_TS = 1_754_600_000.0  # fixed: the fixture must regenerate identically


def make_goodput_run_fixture():
    """Deterministic span-instrumented preempted-and-resumed run (ISSUE 9).

    Hand-stamped event logs — NOT a real training run (real runs stamp wall
    clocks; a golden fixture must be byte-stable). The shape mirrors what a
    span-instrumented, supervised `basic_l1_sweep` writes across one
    preemption: generation 0 loads/trains two chunks (a compile event rides
    inside the first step span), drains a preemption checkpoint, and exits
    preempted; the supervisor restarts it after a 1.2 s backoff inside a
    3.0 s gap; generation 1 restores, finishes, and exports.

    Every second is accounted by construction (23.0 s total wall):

        step 12.2 | compile 2.0 | data_wait 2.7 | checkpoint 0.8
        | preempt_drain 0.7 | restart_backoff 1.2 | preempted_down 1.8
        | unaccounted 1.6   →  goodput 53.0%

    `tests/test_goodput.py` pins the ledger sums, the Chrome-trace schema,
    and the timeline CLI's `--goodput-floor 50` exit codes (0 here; 1 after
    an injected stall) against this directory in tier-1.
    """
    GOODPUT_RUN_DIR.mkdir(parents=True, exist_ok=True)
    T = GOODPUT_BASE_TS
    seq = 0

    def rec(ts, event, **fields):
        nonlocal seq
        seq += 1
        return {"seq": seq, "ts": round(ts, 3), "event": event, **fields}

    def span_rec(ts_start, seconds, category, name, **fields):
        return rec(ts_start + seconds, "span", category=category, name=name,
                   ts_start=round(ts_start, 3), seconds=seconds, **fields)

    fp = {"python": "3.11.8", "jax": "0.6.0", "backend": "cpu",
          "device_kind": "golden-cpu", "device_count": 1, "git_sha": "g0lden"}
    gen0 = [
        rec(T, "run_start", run_name="goodput_golden", generation=0,
            config={"batch": 512, "l1_values": [1e-4, 1e-3]}, fingerprint=fp),
        span_rec(T + 1.0, 1.0, "data_wait", "chunk_load", chunk=0),
        rec(T + 2.0, "chunk_start", chunk=0, position=0),
        # the compile happened INSIDE the step span (tracked_jit measures
        # the dispatch that compiled): the ledger's innermost-wins sweep
        # must count [T+2.5, T+4.5] as compile and shrink step to 3.0 s
        rec(T + 4.5, "compile", name="ensemble.step_scan", seconds=2.0),
        span_rec(T + 2.0, 5.0, "step", "chunk_train", chunk=0),
        rec(T + 7.0, "chunk_end", chunk=0, position=0, seconds=5.0, steps=24),
        span_rec(T + 7.0, 0.8, "data_wait", "chunk_load", chunk=1),
        rec(T + 7.8, "chunk_start", chunk=1, position=1),
        span_rec(T + 7.8, 4.0, "step", "chunk_train", chunk=1),
        rec(T + 11.8, "chunk_end", chunk=1, position=1, seconds=4.0, steps=24),
        span_rec(T + 11.8, 0.7, "preempt_drain", "save:preempt", cursor=1),
        rec(T + 12.5, "checkpoint", path="ckpt_1", cursor=1, reason="preempt"),
        rec(T + 12.55, "preempt", signum=15, checkpoint="ckpt_1", cursor=1),
        rec(T + 12.58, "snapshot",
            counters={"chunks": 2, "train.steps": 48, "checkpoints": 1},
            gauges={}),
        rec(T + 12.6, "run_end", status="preempted", generation=0, steps=48,
            wall_seconds=12.6),
    ]
    seq = 0
    G1 = T + 15.6  # 3.0 s inter-generation gap (1.2 s of it backoff)
    gen1 = [
        rec(G1, "run_start", run_name="goodput_golden", generation=1,
            config={"batch": 512, "l1_values": [1e-4, 1e-3]}, fingerprint=fp),
        span_rec(G1 + 0.1, 0.4, "checkpoint", "restore"),
        rec(G1 + 0.55, "resume", checkpoint="ckpt_1",
            cursor={"chunk": 1, "epoch": 0, "position": 1}),
        span_rec(G1 + 0.6, 0.9, "data_wait", "chunk_load", chunk=2),
        rec(G1 + 1.5, "chunk_start", chunk=2, position=2),
        span_rec(G1 + 1.5, 5.2, "step", "chunk_train", chunk=2),
        rec(G1 + 6.7, "chunk_end", chunk=2, position=2, seconds=5.2, steps=24),
        span_rec(G1 + 6.7, 0.4, "checkpoint", "export"),
        rec(G1 + 7.3, "snapshot",
            counters={"chunks": 1, "train.steps": 24, "resumes": 1},
            gauges={}),
        rec(G1 + 7.4, "run_end", status="ok", generation=1, steps=24,
            wall_seconds=7.4),
    ]
    with open(GOODPUT_RUN_DIR / "events.jsonl", "w") as f:
        for e in gen0 + gen1:
            f.write(json.dumps(e) + "\n")
    seq = 0
    run_dir = "out/goodput_run"  # basename matches: relocatable join
    sup = [
        rec(T - 0.5, "run_start", run_name="supervisor", generation=0,
            config={"cmd": ["python", "-m", "driver"], "max_restarts": 8,
                    "restart_on": "preempt"}),
        rec(T - 0.2, "spawn", attempt=0, generation=0, run_dir=run_dir,
            cmd=["python", "-m", "driver"], resume=False),
        span_rec(T + 14.3, 1.2, "restart_backoff", "backoff", run_dir=run_dir),
        rec(T + 15.55, "restart", attempt=1, generation=1, run_dir=run_dir,
            exit_code=75, classification="preempt", backoff_seconds=1.2,
            downtime_seconds=3.0),
        rec(T + 15.58, "spawn", attempt=1, generation=1, run_dir=run_dir,
            cmd=["python", "-m", "driver"], resume=True),
        rec(G1 + 7.5, "run_end", status="ok", run_name="supervisor",
            generation=0, wall_seconds=23.6),
    ]
    with open(GOODPUT_RUN_DIR / "supervisor_events.jsonl", "w") as f:
        for e in sup:
            f.write(json.dumps(e) + "\n")
    print(f"Wrote {GOODPUT_RUN_DIR}/events.jsonl + supervisor_events.jsonl")


SERVE_RUN_DIR = REPO / "tests" / "golden" / "serve_run"
SERVE_BASE_TS = 1_754_500_000.0  # fixed: the fixture must regenerate identically


def make_serve_run_fixture():
    """Deterministic serving-run fixture (ISSUE 10 satellite): a
    hand-stamped `serve` event log pinning the report "Serving" section and
    the monitor `serve:` line, plus a bench-style JSON pinning the bench
    ``serve`` block schema for the tier-1 perfdiff smoke.

    Hand-stamped, not a real run — golden fixtures must be byte-stable.
    The shape mirrors what `serve.server` writes across one load + SIGTERM
    drain: 4 dicts registered, 96 requests drained into 12 micro-batches
    (request_wait/encode/dequant spans, serve.* counters + SLO gauges in
    the closing snapshot), then a clean drain."""
    SERVE_RUN_DIR.mkdir(parents=True, exist_ok=True)
    T = SERVE_BASE_TS
    seq = 0

    def rec(ts, event, **fields):
        nonlocal seq
        seq += 1
        return {"seq": seq, "ts": round(ts, 3), "event": event, **fields}

    def span_rec(ts_start, seconds, category, name, **fields):
        return rec(ts_start + seconds, "span", category=category, name=name,
                   ts_start=round(ts_start, 3), seconds=seconds, **fields)

    fp = {"python": "3.11.8", "jax": "0.6.0", "backend": "cpu",
          "device_kind": "golden-cpu", "device_count": 1, "git_sha": "g0lden"}
    events = [
        rec(T, "run_start", run_name="serve", generation=0,
            config={"exports": ["out/learned_dicts.pkl"], "weights": "native",
                    "max_batch": 128, "max_wait_ms": 2.0,
                    "dicts": ["d0", "d1", "d2", "d3"]},
            fingerprint=fp),
    ]
    for i in range(4):
        events.append(rec(
            T + 0.1 + 0.01 * i, "serve_dict_added", dict=f"d{i}",
            weights="native", source="out/learned_dicts.pkl",
        ))
    events.append(rec(
        T + 0.2, "serve_subject_attached", subject="subject", layer=2,
        layer_loc="residual", activation_size=512,
    ))
    # 12 micro-batches over ~6 s: each 8 requests x 2 rows -> bucket 16
    t = T + 1.0
    for b in range(12):
        events.append(span_rec(t, 0.004, "request_wait", "queue",
                               n_requests=8, mean_wait_ms=2.1))
        events.append(span_rec(t + 0.004, 0.031, "encode",
                               "encode_g4_b16", lanes=4, rows=16, bucket=16,
                               n_requests=8))
        t += 0.5
    # one int8-resident batch rides a dequant span — NESTED inside its
    # encode window, exactly as the engine emits it (the dequant dispatch
    # happens inside the timed encode window in `_run_group`); the ledger's
    # innermost-wins sweep must attribute the overlap to dequant
    events.append(span_rec(t, 0.006, "dequant", "dequant_int8", lanes=4))
    events.append(span_rec(t, 0.040, "encode", "encode_g4_b16",
                           lanes=4, rows=16, bucket=16, n_requests=8))
    # one sparse top-k batch (k rides the encode span) and one fused
    # /features batch (2 sequences x 32 tokens through the subject LM) —
    # the ISSUE-15 event shapes the report/monitor must keep rendering
    events.append(span_rec(t + 0.5, 0.022, "encode", "encode_g4_b16",
                           lanes=4, rows=16, bucket=16, n_requests=8, k=16))
    events.append(span_rec(t + 1.0, 0.055, "encode", "features_g4_s2x32",
                           lanes=4, rows=64, bucket=64, n_requests=1,
                           subject="subject"))
    counters = {
        "serve.requests": 96, "serve.rows": 192, "serve.batches": 13,
        "serve.padded_rows": 16, "serve.rejected": 2, "serve.errors": 0,
        "serve.compiles": 3,
        # wire accounting (ISSUE 15): per-format requests + bytes, sparse
        # and fused-features traffic — the report's wire lines read these
        "serve.requests.json": 64, "serve.requests.npz": 24,
        "serve.requests.raw": 8,
        "serve.bytes_out.json": 6553600, "serve.bytes_out.npz": 28672,
        "serve.bytes_out.raw": 6144,
        "serve.bytes_in.json": 262144, "serve.bytes_in.npz": 40960,
        "serve.bytes_in.raw": 8192,
        "serve.sparse_requests": 32, "serve.feature_requests": 8,
        "span.request_wait.count": 12, "span.request_wait.seconds": 0.048,
        "span.encode.count": 15, "span.encode.seconds": 0.489,
        "span.dequant.count": 1, "span.dequant.seconds": 0.006,
    }
    gauges = {
        "serve.queue_depth": 0, "serve.batch_occupancy": 0.875,
        "serve.latency_p50_ms": 8.3, "serve.latency_p95_ms": 14.9,
        "serve.latency_p99_ms": 21.4,
    }
    events.append(rec(T + 8.0, "serve_drain", queue_depth=3))
    events.append(rec(T + 8.4, "serve_drained", signum=15, requests=96))
    events.append(rec(T + 8.5, "snapshot", counters=counters, gauges=gauges))
    events.append(rec(T + 8.6, "run_end", status="drained", run_name="serve",
                      generation=0, wall_seconds=8.6))
    with open(SERVE_RUN_DIR / "events.jsonl", "w") as f:
        for e in events:
            f.write(json.dumps(e) + "\n")

    # bench-style JSON pinning the serve block schema for perfdiff: medians
    # + spreads for the two gated keys, the pinned control, and the derived
    # `serve` dict (which perfdiff ignores — only *_spread keys gate)
    bench = {
        "metric": "serve_fixture (golden: schema pin for the bench serve block)",
        "control_matmul_tflops": 0.21,
        "control_matmul_tflops_spread": [0.2, 0.22],
        "serve_rows_per_sec": 420.0,
        "serve_rows_per_sec_spread": [395.0, 445.0],
        "serve_naive_rows_per_sec": 100.0,
        "serve_naive_rows_per_sec_spread": [92.0, 110.0],
        # wire-format keys (ISSUE 15): r06 CPU-floor medians. The bytes
        # keys are LOWER-is-better (perfdiff gates them inverted); the
        # ~86x dense-JSON/sparse-npz ratio at n_feats 4096 is the
        # measured acceptance evidence, schema-pinned here.
        "serve_json_rows_per_sec": 210.0,
        "serve_json_rows_per_sec_spread": [194.0, 218.0],
        "serve_npz_rows_per_sec": 400.0,
        "serve_npz_rows_per_sec_spread": [380.0, 424.0],
        "serve_dense_json_bytes_per_row": 50200.0,
        "serve_dense_json_bytes_per_row_spread": [50150.0, 50250.0],
        "serve_sparse_bytes_per_row": 585.0,
        "serve_sparse_bytes_per_row_spread": [580.0, 590.0],
        "features_rows_per_sec": 2700.0,
        "features_rows_per_sec_spread": [2600.0, 2900.0],
        "serve": {
            "p50_ms": 8.3, "p95_ms": 14.9, "p99_ms": 21.4,
            "requests_per_sec": 210.0, "speedup_vs_naive": 4.2,
            "n_dicts": 4, "batch_budget": 128, "batch_occupancy": 0.875,
            "compiled_steps": 3,
        },
        "serve_wire": {
            "k": 16, "n_feats": 4096,
            "dense_json_bytes_per_row": 50200.0,
            "sparse_npz_bytes_per_row": 585.0,
            "bytes_per_row_ratio": 85.8,
            "npz_speedup_vs_json": 1.9,
        },
    }
    with open(SERVE_RUN_DIR / "bench_serve_fixture.json", "w") as f:
        json.dump(bench, f, indent=1)
    print(f"Wrote {SERVE_RUN_DIR}/events.jsonl + bench_serve_fixture.json")


ROUTER_RUN_DIR = REPO / "tests" / "golden" / "router_run"
ROUTER_BASE_TS = 1_754_600_000.0  # fixed: the fixture must regenerate identically


def make_router_run_fixture():
    """Deterministic replica-tier fixture (ISSUE 13): a hand-stamped run
    dir shaped like what `serve.replicaset` + `serve.router` write — a
    replicaset log, a router log, and three per-replica serve logs (every
    record tagged ``replica``) — pinning the report **Router** section, the
    per-replica Serving merge, and the monitor ``router:`` /
    ``serve[replicaN]:`` lines; plus a bench-style JSON pinning the
    ``router_rows_per_sec`` key + ``router`` block schema for the tier-1
    perfdiff smoke.

    The modeled story: 3 replicas serve 480 requests; replica1 is
    SIGKILLed mid-run (router marks it dead, retries its in-flight
    traffic, supervisor restarts it after backoff), then a rolling swap
    rolls generation 0 → 1 across all three."""
    ROUTER_RUN_DIR.mkdir(parents=True, exist_ok=True)
    T = ROUTER_BASE_TS

    def writer(path):
        seq = {"n": 0}

        def rec(ts, event, **fields):
            seq["n"] += 1
            return {"seq": seq["n"], "ts": round(ts, 3), "event": event,
                    **fields}

        return path, rec, []

    fp = {"python": "3.11.8", "jax": "0.6.0", "backend": "cpu",
          "device_kind": "golden-cpu", "device_count": 1, "git_sha": "g0lden"}

    # -- per-replica serve logs (tagged `replica`) --------------------------
    for i in range(3):
        rid = f"replica{i}"
        d = ROUTER_RUN_DIR / rid
        d.mkdir(parents=True, exist_ok=True)
        _, rec, events = writer(d / "events.jsonl")
        events.append(rec(
            T + 0.2 * i, "run_start", run_name="serve", generation=0,
            replica=rid,
            config={"exports": ["out/learned_dicts.pkl"], "weights": "native",
                    "max_batch": 64, "max_wait_ms": 2.0,
                    "dicts": ["d0", "d1"], "replica_id": rid,
                    "dict_generation": 0},
            fingerprint=fp,
        ))
        for j in range(2):
            events.append(rec(
                T + 0.3 + 0.2 * i + 0.01 * j, "serve_dict_added",
                replica=rid, dict=f"d{j}", weights="native",
                source="out/learned_dicts.pkl",
            ))
        counters = {
            "serve.requests": 160, "serve.rows": 320, "serve.batches": 24,
            "serve.padded_rows": 40, "serve.rejected": 2 if i == 1 else 0,
            "serve.errors": 0,
        }
        gauges = {
            "serve.queue_depth": 0, "serve.batch_occupancy": 0.889,
            "serve.latency_p50_ms": 7.9 + 0.2 * i,
            "serve.latency_p95_ms": 13.8 + 0.2 * i,
            "serve.latency_p99_ms": 19.5 + 0.2 * i,
        }
        events.append(rec(T + 24.0 + 0.2 * i, "snapshot", replica=rid,
                          counters=counters, gauges=gauges))
        events.append(rec(T + 24.5 + 0.2 * i, "run_end", status="drained",
                          replica=rid, run_name="serve", generation=0,
                          wall_seconds=24.5))
        with open(d / "events.jsonl", "w") as f:
            for e in events:
                f.write(json.dumps(e) + "\n")

    # -- router log ---------------------------------------------------------
    _, rec, events = writer(ROUTER_RUN_DIR / "router_events.jsonl")
    events.append(rec(T, "run_start", run_name="router", generation=0,
                      config={"replicas": 3, "hedge_ms": 20.0,
                              "max_inflight": 64},
                      fingerprint=fp))
    for i in range(3):
        events.append(rec(T + 0.5 + 0.05 * i, "router_replica_state",
                          replica=f"replica{i}", frm="suspect", to="live",
                          reason="probe_ok"))
    # replica1 SIGKILLed: forward fails -> suspect -> marked dead by the
    # replicaset, restarted, readmitted
    events.append(rec(T + 9.0, "router_replica_state", replica="replica1",
                      frm="live", to="suspect", reason="ConnectionResetError"))
    events.append(rec(T + 9.1, "router_replica_state", replica="replica1",
                      frm="suspect", to="dead", reason="killed"))
    events.append(rec(T + 11.3, "router_replica_state", replica="replica1",
                      frm="dead", to="live", reason="admitted"))
    # rolling swap: each replica drains (no penalty) and readmits
    for i, t_off in enumerate((16.0, 18.0, 20.0)):
        rid = f"replica{i}"
        events.append(rec(T + t_off, "router_replica_quiesced", replica=rid))
        events.append(rec(T + t_off + 0.3, "router_replica_state",
                          replica=rid, frm="live", to="dead",
                          reason="marked_down"))
        events.append(rec(T + t_off + 1.6, "router_replica_state",
                          replica=rid, frm="dead", to="live",
                          reason="admitted"))
        events.append(rec(T + t_off + 1.7, "router_replica_readmitted",
                          replica=rid))
    counters = {
        "router.requests": 482, "router.ok": 478, "router.retried_ok": 7,
        "router.client_errors": 2, "router.retries": 9, "router.hedges": 2,
        "router.sheds": 2, "router.failed": 0, "router.forwards": 489,
        "router.state_changes": 16,
    }
    gauges = {
        "router.replicas": 3, "router.live_replicas": 3,
        "router.inflight": 0,
        "router.replica.replica0.p50_ms": 8.1,
        "router.replica.replica0.p99_ms": 20.3,
        "router.replica.replica1.p50_ms": 8.4,
        "router.replica.replica1.p99_ms": 23.1,
        "router.replica.replica2.p50_ms": 8.2,
        "router.replica.replica2.p99_ms": 21.0,
        "router.replica.replica0.state": 0,
        "router.replica.replica1.state": 0,
        "router.replica.replica2.state": 0,
    }
    events.append(rec(T + 24.8, "snapshot", counters=counters, gauges=gauges))
    events.append(rec(T + 25.0, "run_end", status="drained",
                      run_name="router", generation=0, wall_seconds=25.0))
    with open(ROUTER_RUN_DIR / "router_events.jsonl", "w") as f:
        for e in events:
            f.write(json.dumps(e) + "\n")

    # -- replicaset log -----------------------------------------------------
    _, rec, events = writer(ROUTER_RUN_DIR / "replicaset_events.jsonl")
    events.append(rec(T, "run_start", run_name="replicaset", generation=0,
                      config={"exports": ["out/learned_dicts.pkl"],
                              "replicas": 3, "weights": "native",
                              "max_batch": 64},
                      fingerprint=fp))
    events.append(rec(T + 0.05, "replicaset_start", replicas=3))
    for i in range(3):
        rid = f"replica{i}"
        events.append(rec(T + 0.1 + 0.05 * i, "replica_spawn", replica=rid,
                          generation=0, pid=41000 + i,
                          exports=["out/learned_dicts.pkl"]))
        events.append(rec(T + 0.4 + 0.05 * i, "replica_ready", replica=rid,
                          url=f"http://127.0.0.1:{8770 + i}", generation=0,
                          downtime_seconds=None))
    # the SIGKILL: exit classified, backoff span, restart, readmission
    events.append(rec(T + 9.05, "replica_exit", replica="replica1",
                      exit_code=-9, classification="killed", generation=0))
    events.append(rec(T + 9.55, "span", category="restart_backoff",
                      name="replica_backoff", ts_start=round(T + 9.05, 3),
                      seconds=0.5, replica="replica1"))
    events.append(rec(T + 9.55, "replica_restart", replica="replica1",
                      attempt=1, classification="killed",
                      backoff_seconds=0.5))
    events.append(rec(T + 9.6, "replica_spawn", replica="replica1",
                      generation=0, pid=41017,
                      exports=["out/learned_dicts.pkl"]))
    events.append(rec(T + 11.3, "replica_ready", replica="replica1",
                      url="http://127.0.0.1:8793", generation=0,
                      downtime_seconds=2.25))
    # the rolling swap, one replica at a time
    events.append(rec(T + 15.8, "rolling_swap_start", from_generation=0,
                      to_generation=1, replicas=3))
    for i, t_off in enumerate((16.0, 18.0, 20.0)):
        rid = f"replica{i}"
        events.append(rec(T + t_off + 0.4, "replica_drained", replica=rid,
                          exit_code=0, seconds=0.4))
        events.append(rec(T + t_off + 0.5, "replica_spawn", replica=rid,
                          generation=1, pid=41020 + i,
                          exports=["out/learned_dicts_v2.pkl"]))
        events.append(rec(T + t_off + 1.6, "replica_ready", replica=rid,
                          url=f"http://127.0.0.1:{8800 + i}", generation=1,
                          downtime_seconds=None))
        events.append(rec(T + t_off + 1.7, "replica_swapped", replica=rid,
                          generation=1))
    events.append(rec(T + 21.8, "rolling_swap_done", generation=1,
                      replicas=3, seconds=6.0))
    counters = {
        "replicaset.deaths": 1, "replicaset.deaths.killed": 1,
        "replicaset.restarts": 1, "replicaset.restarts.killed": 1,
        "replicaset.swaps": 1,
        "span.restart_backoff.count": 1,
        "span.restart_backoff.seconds": 0.5,
    }
    events.append(rec(T + 24.9, "snapshot", counters=counters, gauges={}))
    events.append(rec(T + 25.1, "run_end", status="drained",
                      run_name="replicaset", generation=0,
                      wall_seconds=25.1))
    with open(ROUTER_RUN_DIR / "replicaset_events.jsonl", "w") as f:
        for e in events:
            f.write(json.dumps(e) + "\n")

    # bench-style JSON pinning the router keys + block schema for perfdiff
    bench = {
        "metric": "router_fixture (golden: schema pin for the bench router block)",
        "control_matmul_tflops": 0.21,
        "control_matmul_tflops_spread": [0.2, 0.22],
        "router_rows_per_sec": 390.0,
        "router_rows_per_sec_spread": [370.0, 405.0],
        "router_direct_rows_per_sec": 430.0,
        "router_direct_rows_per_sec_spread": [410.0, 450.0],
        "router": {
            "overhead_ratio": 0.907, "retries": 0, "hedges": 0,
            "sheds": 0, "failed": 0, "client_errors": 0, "replicas": 1,
        },
    }
    with open(ROUTER_RUN_DIR / "bench_router_fixture.json", "w") as f:
        json.dump(bench, f, indent=1)
    print(f"Wrote {ROUTER_RUN_DIR}/ (replicaset/router/replica logs + "
          "bench_router_fixture.json)")


BENCH_FIXTURE = REPO / "tests" / "golden" / "bench_fixture.json"


def make_bench_fixture():
    """Regenerate tests/golden/bench_fixture.json — the perfdiff tier-1
    smoke's schema pin (tests/test_perfdiff.py).

    Two provenance classes, recorded in ``fixture_note``:
      - the r05-era keys carry the REAL TPU-v5e medians/spreads measured in
        BENCH_r05.json's session (copied verbatim — do not invent);
      - the round-6 keys (topk_fused_steps_per_sec,
        headline_int8mom_acts_per_sec, recompute_code_acts_per_sec) are
        MODELED pins stamped from THROUGHPUT round-6 arithmetic so the
        comparator exercises the new schema — an ISSUE-12 session had no
        TPU; the first on-chip bench run replaces them with measurements
        (and perfdiff reports them as "new" against older envelopes either
        way). Values only shape the smoke tests, which compare the fixture
        against itself.
    """
    bench = {
        "metric": (
            "ensemble_sae_train_throughput "
            "(8x tied-SAE 512->4096, batch 2048, bf16+scan128)"
        ),
        "fixture_note": (
            "perfdiff schema pin; r05 keys measured on TPU v5 lite, "
            "round-6 keys (topk_fused/int8mom/recompute_code) and the "
            "ISSUE-17 featstats keys (headline_featstats/headline_"
            "nofeatstats/serve_featstats — both headline keys pin the "
            "UNFUSED path, the sketch reads the code tensor the fused "
            "kernel never materializes) are MODELED placeholders pending "
            "a TPU session — see scripts/make_golden_fixture.py "
            "--bench-fixture"
        ),
        "value": 818039.4,
        "unit": "activations/sec/chip",
        "mfu": 0.697,
        "device": "TPU v5 lite",
        "rounds": 5,
        "value_spread": [816556.6, 818505.8],
        "harvest_tokens_per_sec": 26631.6,
        "harvest_tokens_per_sec_spread": [23686.8, 27856.2],
        "stream_rows_per_sec": 48993.7,
        "stream_rows_per_sec_spread": [47237.8, 50142.8],
        "fista500_codes_per_sec": 2058.1,
        "fista500_codes_per_sec_spread": [1704.4, 2141.6],
        "topk_steps_per_sec": 30.1,
        "topk_steps_per_sec_spread": [30.0, 32.5],
        # round-6 modeled pins (see fixture_note): fused TopK at ~0.6 MFU of
        # its 1.35 TFLOP/step (~68 steps/s vs the 30.1 XLA path) ...
        "topk_fused_steps_per_sec": 68.0,
        "topk_fused_steps_per_sec_spread": [64.0, 71.0],
        "control_matmul_tflops": 60.3,
        "control_matmul_tflops_spread": [54.6, 60.6],
        "bigbatch16k_acts_per_sec": 802482.5,
        "bigbatch16k_acts_per_sec_spread": [759208.9, 804113.7],
        # ... int8-mu headline modeled ~flat (r5b: the moment stream was
        # already overlapped) ...
        "headline_int8mom_acts_per_sec": 820000.0,
        "headline_int8mom_acts_per_sec_spread": [812000.0, 828000.0],
        # ... and code-recompute at r5b's modeled 0.775/0.69 five-pass MFU
        # ratio over the measured headline, discounted for overlap
        "recompute_code_acts_per_sec": 860000.0,
        "recompute_code_acts_per_sec_spread": [845000.0, 882000.0],
        "topk_fused_is_fused": True,
        "topk_fused_speedup": 2.26,
        "control_fraction_of_peak": 0.306,
        # ISSUE-14 sensor-layer guard: full telemetry.slo evaluations per
        # second over a synthetic 10k-event run dir (host-side, measured on
        # this repo's CPU CI box — the key is chip-independent). Perfdiff
        # gates it so the SLO engine never becomes the bottleneck it is
        # supposed to watch.
        "slo_eval_runs_per_sec": 15.0,
        "slo_eval_runs_per_sec_spread": [13.5, 16.5],
        # ISSUE-16 sclint guard: full static-analysis passes over the
        # shipped tree in files/second (host-side, chip-independent;
        # measured on this repo's CPU CI box). The floor keeps the lint
        # pass cheap enough that check.sh/CI never skip it — a rule that
        # re-parses the world on every walk would trip this before it
        # trips a human's patience.
        "sclint_files_per_sec": 37.0,
        "sclint_files_per_sec_spread": [25.0, 50.0],
        # ISSUE-17 feature-sketch guards, modeled (see fixture_note). The
        # acceptance floor is featstats.overhead_frac <= 0.02: the sketch's
        # extra work per step is a handful of [B, F] elementwise reductions
        # against the XLA step's matmul pair, modeled ~1.2% at the bench
        # shape. The serve sketch adds pure on-device jnp updates after
        # dispatch — modeled at parity with serve_rows_per_sec.
        "headline_featstats_acts_per_sec": 553000.0,
        "headline_featstats_acts_per_sec_spread": [545000.0, 560000.0],
        "headline_nofeatstats_acts_per_sec": 560000.0,
        "headline_nofeatstats_acts_per_sec_spread": [552000.0, 567000.0],
        "serve_featstats_rows_per_sec": 415.0,
        "serve_featstats_rows_per_sec_spread": [390.0, 440.0],
        "featstats": {"overhead_frac": 0.0125, "serve_ratio": 0.988},
        # ISSUE-18 control-tower guards (host-side, chip-independent;
        # measured on this repo's CPU CI box). The scrape key is full
        # Tower.poll_once cycles over 4 fake replica endpoints in
        # targets/second — scrape + parse + merge + series-store record +
        # burn-rate rule evaluation + series.jsonl append all on the
        # clock. The twin keys run the SAME closed-loop HTTP serve load
        # with and without a 20 Hz tower watching the replica; the
        # acceptance contract is tower.overhead_frac <= 0.02 — the
        # watcher must never become the load it is measuring.
        "tower_scrape_targets_per_sec": 450.0,
        "tower_scrape_targets_per_sec_spread": [400.0, 500.0],
        "serve_watched_rows_per_sec": 440.0,
        "serve_watched_rows_per_sec_spread": [415.0, 465.0],
        "serve_unwatched_rows_per_sec": 445.0,
        "serve_unwatched_rows_per_sec_spread": [420.0, 470.0],
        "tower": {"overhead_frac": 0.0112, "watch_hz": 20.0,
                  "scrape_targets": 4},
        # ISSUE-19 provenance guard (host-side, chip-independent; measured
        # on this repo's CPU CI box). Artifact nodes reconstructed per
        # second by telemetry.provenance.build_graph over a 200-chunk
        # store + run + checkpoint + export estate — `lineage check` runs
        # in check.sh/CI and the tower folds taint lists into incident
        # context, so graph reconstruction must stay cheap at fleet scale.
        "lineage_nodes_per_sec": 3600.0,
        "lineage_nodes_per_sec_spread": [3100.0, 4100.0],
    }
    with open(BENCH_FIXTURE, "w") as f:
        json.dump(bench, f, indent=1)
        f.write("\n")
    print(f"Wrote {BENCH_FIXTURE}")


FLEET_RUN_DIR = REPO / "tests" / "golden" / "fleet_run"
FLEET_BASE_TS = 1_754_400_000.0  # fixed: the fixture must regenerate identically


def make_fleet_run_fixture():
    """Deterministic finished-fleet directory (ISSUE 6 satellite).

    Hand-stamped queue/event files — NOT a real fleet run: real runs stamp
    wall clocks, and a golden fixture must be byte-stable. The shape mirrors
    what `fleet/` leaves behind after a night of churn: two done items (four
    members, zero lost), a reassignment lineage where w0 lost g0's lease and
    w1 resumed it from `ckpt_1`, a repeat offender (w2, three lost leases)
    quarantined, and the scheduler's event log. `tests/test_fleet.py` pins
    `fleet.report` and the monitor's fleet view against this directory in
    tier-1.
    """
    t = FLEET_BASE_TS
    queue = FLEET_RUN_DIR / "queue"
    for bucket in ("pending", "leased", "done", "failed", "leases", "workers",
                   "seen"):
        (queue / bucket).mkdir(parents=True, exist_ok=True)
    for bucket in ("pending", "leased", "failed", "leases"):
        # git drops empty dirs, but is_fleet_dir/WorkQueue need the layout
        (queue / bucket / ".gitkeep").write_text("")

    items = {
        "g0": {
            "item": "g0",
            "members": ["l1_1.00e-04", "l1_3.16e-04"],
            "payload": {"driver": "basic_l1_sweep",
                        "kwargs": {"l1_values": [1e-4, 3.16e-4]}},
            "attempt": 1,
            "submitted_ts": t,
            "lineage": [
                {"attempt": 0, "worker": "w0", "claimed_ts": t + 1.0,
                 "outcome": "lease_expired", "released_ts": t + 40.0,
                 "lease_age_seconds": 31.5},
                {"attempt": 1, "worker": "w1", "claimed_ts": t + 45.0,
                 "outcome": "done", "resumed_from": "ckpt_1",
                 "completed_ts": t + 90.0},
            ],
            "result": {"export_manifest": "export_manifest.json",
                       "verified": True},
        },
        "g1": {
            "item": "g1",
            "members": ["l1_1.00e-03", "l1_3.16e-03"],
            "payload": {"driver": "basic_l1_sweep",
                        "kwargs": {"l1_values": [1e-3, 3.16e-3]}},
            "attempt": 3,
            "submitted_ts": t,
            "lineage": [
                {"attempt": k, "worker": "w2", "claimed_ts": t + 2.0 + 20 * k,
                 "outcome": "lease_expired", "released_ts": t + 14.0 + 20 * k,
                 "lease_age_seconds": 10.0}
                for k in range(3)
            ] + [
                {"attempt": 3, "worker": "w1", "claimed_ts": t + 95.0,
                 "outcome": "done", "resumed_from": "ckpt_0",
                 "completed_ts": t + 130.0},
            ],
            "result": {"export_manifest": "export_manifest.json",
                       "verified": True},
        },
    }
    for item_id, item in items.items():
        with open(queue / "done" / f"{item_id}.json", "w") as f:
            json.dump(item, f)
    # ledger (scheduler-owned: strikes/quarantine) + seen (worker-owned
    # liveness) are separate single-writer files; per-worker done counts
    # are derived from item lineage, never stored
    workers = {
        "w0": {"worker": "w0", "strikes": 1,
               "strike_reasons": ["lease_expired:g0"], "quarantined": False},
        "w2": {"worker": "w2", "strikes": 3,
               "strike_reasons": ["lease_expired:g1"] * 3, "quarantined": True},
    }
    for wid, rec in workers.items():
        with open(queue / "workers" / f"{wid}.json", "w") as f:
            json.dump(rec, f)
    for wid, seen_ts in (("w0", t + 100.0), ("w1", t + 130.0), ("w2", t + 60.0)):
        with open(queue / "seen" / f"{wid}.json", "w") as f:
            json.dump({"worker": wid, "last_seen_ts": seen_ts}, f)

    # the scheduler's own event log (RunTelemetry record shape)
    seq = 0
    ts = t

    def rec(event, dt=1.0, **fields):
        nonlocal seq, ts
        seq += 1
        ts += dt
        return {"seq": seq, "ts": round(ts, 3), "event": event, **fields}

    sched = [
        rec("run_start", run_name="fleet_scheduler",
            config={"lease_seconds": 30.0, "max_attempts": 5,
                    "quarantine_after": 3}),
        rec("lease_expired", dt=39.0, item="g0", worker="w0", attempt=1,
            requeued_to="pending"),
        rec("lease_expired", dt=-26.0, item="g1", worker="w2", attempt=1,
            requeued_to="pending"),
        rec("lease_expired", dt=20.0, item="g1", worker="w2", attempt=2,
            requeued_to="pending"),
        rec("lease_expired", dt=20.0, item="g1", worker="w2", attempt=3,
            requeued_to="pending"),
        rec("quarantine", dt=0.1, worker="w2", strikes=3),
        rec("fleet_done", dt=57.0,
            items={"pending": 0, "leased": 0, "done": 2, "failed": 0},
            members={"queued": 0, "running": 0, "orphaned": 0, "done": 4,
                     "lost": 0}),
        rec("run_end", dt=0.1, status="ok", wall_seconds=131.2),
    ]
    with open(FLEET_RUN_DIR / "scheduler_events.jsonl", "w") as f:
        for e in sched:
            f.write(json.dumps(e) + "\n")

    # per-item run dirs: just enough events for the report's item rollup
    for item_id, resumes, steps in (("g0", 1, 24), ("g1", 1, 24)):
        run_dir = FLEET_RUN_DIR / "runs" / item_id
        run_dir.mkdir(parents=True, exist_ok=True)
        seq, ts = 0, t
        run = [
            rec("run_start", run_name=f"fleet_{item_id}",
                config={"l1_values": items[item_id]["payload"]["kwargs"]["l1_values"]},
                fingerprint={"python": "3.11.8", "jax": "0.6.0",
                             "backend": "cpu", "device_kind": "golden-cpu",
                             "device_count": 1, "git_sha": "g0lden"}),
            rec("resume", checkpoint=f"ckpt_{1 if item_id == 'g0' else 0}",
                cursor={"chunk": 1, "epoch": 0, "position": 1}),
            rec("snapshot", dt=40.0,
                counters={"chunks": 2, "train.steps": steps,
                          "resumes": resumes, "checkpoints": 2},
                gauges={}),
            rec("run_end", dt=1.0, status="ok", steps=steps,
                wall_seconds=43.0),
        ]
        with open(run_dir / "events.jsonl", "w") as f:
            for e in run:
                f.write(json.dumps(e) + "\n")
    print(f"Wrote {FLEET_RUN_DIR}/queue + scheduler_events.jsonl + runs/")


CORRUPT_STORE_DIR = REPO / "tests" / "golden" / "corrupt_store"
CORRUPT_BASE_TS = 1_754_500_000.0  # fixed: the fixture must regenerate identically


def make_corrupt_store_fixture():
    """Deterministic chunk store with known-bad chunks (ISSUE 8 satellite).

    A five-chunk store exercising every row of the DATAPLANE failure
    matrix: two good chunks (fp16 + int8), a bit-flipped committed chunk
    (sizes intact — only the digest tier catches it), a committed int8
    chunk whose scale file was deleted (missing-file vs manifest), and a
    LEGACY int8 chunk (no manifest) with no scale file — the pre-manifest
    format's silent-misread case, pinned as *detected*. Chunk data is
    seeded numpy; manifest timestamps are re-stamped to a fixed value so
    the fixture is byte-stable. `tests/test_data_integrity.py` copies this
    directory and pins the scrub CLI's report rendering and exit code
    against it in tier-1."""
    import json as _json

    import numpy as np

    from sparse_coding__tpu.data import integrity
    from sparse_coding__tpu.data.chunks import chunk_path, save_chunk, scale_path

    CORRUPT_STORE_DIR.mkdir(parents=True, exist_ok=True)
    rng = np.random.default_rng(8)
    data = rng.standard_normal((64, 16)).astype(np.float32)
    save_chunk(CORRUPT_STORE_DIR, 0, data)                   # good fp16
    save_chunk(CORRUPT_STORE_DIR, 1, data * 2, dtype=np.int8)  # good int8
    save_chunk(CORRUPT_STORE_DIR, 2, data + 1)               # to be bit-flipped
    save_chunk(CORRUPT_STORE_DIR, 3, data - 1, dtype=np.int8)  # scale to vanish
    # chunk 2: bit rot AFTER commit — size intact, digest wrong
    p = chunk_path(CORRUPT_STORE_DIR, 2)
    raw = bytearray(p.read_bytes())
    raw[-1] ^= 0xFF
    p.write_bytes(bytes(raw))
    # chunk 3: committed pair whose scale side file went missing
    scale_path(CORRUPT_STORE_DIR, 3).unlink()
    # chunk 4: LEGACY torn pair — int8 bytes, no scale, no manifest (the
    # pre-manifest silent misread, now detected structurally)
    np.save(chunk_path(CORRUPT_STORE_DIR, 4), (data * 3).astype(np.int8))
    # byte-stability: pin every manifest's created_at
    for i in range(4):
        mp = integrity.chunk_manifest_path(CORRUPT_STORE_DIR, i)
        manifest = _json.loads(mp.read_text())
        manifest["created_at"] = CORRUPT_BASE_TS
        mp.write_text(_json.dumps(manifest))
    print(f"Wrote {CORRUPT_STORE_DIR} (chunks 0-1 good, 2 bit-flipped, "
          "3 missing scale, 4 legacy torn)")


TRACED_RUN_DIR = REPO / "tests" / "golden" / "traced_run"
TRACED_BASE_TS = 1_754_700_000.0  # fixed: the fixture must regenerate identically
# fixed trace ids, readable on purpose
TRACE_RETRIED = "aaaa1111aaaa1111aaaa1111aaaa1111"
TRACE_FAST = "bbbb2222bbbb2222bbbb2222bbbb2222"
TRACE_TAIL = "cccc3333cccc3333cccc3333cccc3333"
_HIST_BOUNDS = [0.25 * 2 ** i for i in range(14)]


def make_traced_run_fixture():
    """Deterministic request-tracing + SLO fixture (ISSUE 14): a
    hand-stamped router + 2-replica run dir whose events carry the full
    trace vocabulary — ``forward`` attempt spans (including one retried
    request with child spans on BOTH replicas), per-request
    ``request_trace`` records, trace-tagged batch spans, and snapshot
    histograms — plus an ``slo.json`` the run satisfies and an
    ``slo_strict.json`` it violates. Pins, in tier-1: the trace CLI's
    reconstruction and --slowest output, the slo CLI's verdicts and exit
    codes (0 within / 1 past budget), and the report's SLO section.

    Hand-stamped, not a real run — golden fixtures must be byte-stable.
    The modeled story: 3 requests; TRACE_RETRIED's first forward to
    replica0 dies mid-flight (transport error), the retry lands on
    replica1; TRACE_FAST serves from replica0 in 6 ms; TRACE_TAIL is the
    p99 tail — 31 ms, dominated by queue wait in a crowded bucket."""
    TRACED_RUN_DIR.mkdir(parents=True, exist_ok=True)
    T = TRACED_BASE_TS

    def writer():
        seq = {"n": 0}

        def rec(ts, event, **fields):
            seq["n"] += 1
            return {"seq": seq["n"], "ts": round(ts, 4), "event": event,
                    **fields}

        return rec

    fp = {"python": "3.11.8", "jax": "0.6.0", "backend": "cpu",
          "device_kind": "golden-cpu", "device_count": 1, "git_sha": "g0lden"}

    # -- router log: forward attempt spans ----------------------------------
    rec = writer()
    events = [
        rec(T, "run_start", run_name="router", generation=0,
            config={"replicas": 2, "max_inflight": 64}, fingerprint=fp),
    ]

    def fwd(ts_start, seconds, trace_id, span_id, replica, attempt, status,
            hedge=False):
        return rec(
            ts_start + seconds, "span", category="forward", name="attempt",
            ts_start=round(ts_start, 4), seconds=seconds, trace_id=trace_id,
            span_id=span_id, parent_span=None, replica=replica,
            attempt=attempt, hedge=hedge, status=status,
        )

    # TRACE_FAST: one clean forward to replica0
    events.append(fwd(T + 5.0, 0.006, TRACE_FAST, "b0b0b0b0b0b0b0b0",
                      "replica0", 0, 200))
    # TRACE_RETRIED: replica0 dies mid-forward, retry wins on replica1
    events.append(fwd(T + 9.0, 0.012, TRACE_RETRIED, "a0a0a0a0a0a0a0a0",
                      "replica0", 0, "error:ConnectionResetError"))
    events.append(fwd(T + 9.062, 0.018, TRACE_RETRIED, "a1a1a1a1a1a1a1a1",
                      "replica1", 1, 200))
    # TRACE_TAIL: slow — crowded bucket on replica1
    events.append(fwd(T + 12.0, 0.031, TRACE_TAIL, "c1c1c1c1c1c1c1c1",
                      "replica1", 0, 200))
    events.append(rec(T + 20.0, "snapshot", counters={
        "router.requests": 3, "router.ok": 3, "router.retried_ok": 1,
        "router.retries": 1, "router.forwards": 4, "router.failed": 0,
        "router.sheds": 0, "span.forward.count": 4,
        "span.forward.seconds": 0.067,
    }, gauges={"router.replicas": 2, "router.live_replicas": 2,
               "router.inflight": 0}))
    events.append(rec(T + 20.5, "run_end", status="drained",
                      run_name="router", generation=0, wall_seconds=20.5))
    with open(TRACED_RUN_DIR / "router_events.jsonl", "w") as f:
        for e in events:
            f.write(json.dumps(e) + "\n")

    # -- per-replica serve logs: request_trace + tagged batch spans ----------
    def replica_log(rid, requests, batch_spans, counters, gauges, hists):
        rec = writer()
        events = [rec(
            T + 0.1, "run_start", run_name="serve", generation=0,
            replica=rid,
            config={"exports": ["out/learned_dicts.pkl"],
                    "weights": "native", "max_batch": 64,
                    "replica_id": rid, "dict_generation": 0},
            fingerprint=fp,
        )]
        for ts_start, seconds, name, traces, fields in batch_spans:
            events.append(rec(
                ts_start + seconds, "span", category=fields.pop("category"),
                name=name, replica=rid, ts_start=round(ts_start, 4),
                seconds=seconds, traces=traces, **fields,
            ))
        for r in requests:
            events.append(rec(r.pop("ts"), "request_trace", replica=rid, **r))
        events.append(rec(T + 19.0, "snapshot", replica=rid,
                          counters=counters, gauges=gauges, hists=hists))
        events.append(rec(T + 19.5, "run_end", status="drained", replica=rid,
                          run_name="serve", generation=0, wall_seconds=19.4))
        d = TRACED_RUN_DIR / rid
        d.mkdir(parents=True, exist_ok=True)
        with open(d / "events.jsonl", "w") as f:
            for e in events:
                f.write(json.dumps(e) + "\n")

    hist0 = {"serve.latency_ms": {
        "bounds": _HIST_BOUNDS,
        "counts": [0, 0, 2, 18, 65, 24, 9, 2, 0, 0, 0, 0, 0, 0, 0],
        "sum": 692.4, "count": 120}}
    replica_log(
        "replica0",
        requests=[{
            "ts": T + 5.006, "trace_id": TRACE_FAST,
            "span_id": "f0f0f0f0f0f0f0f0", "parent_span": "b0b0b0b0b0b0b0b0",
            "dict": "d0", "rows": 2, "ts_start": round(T + 5.001, 4),
            "latency_ms": 4.8,
            "phases": {"request_wait": 0.0018, "encode": 0.0028,
                       "dequant": 0.0},
            "bucket": 8, "lanes": 2, "n_requests": 3,
        }],
        batch_spans=[
            (T + 5.0028, 0.0018, "queue",
             [TRACE_FAST], {"category": "request_wait", "n_requests": 3,
                            "mean_wait_ms": 1.6}),
            (T + 5.0046, 0.0028, "encode_g2_b8",
             [TRACE_FAST], {"category": "encode", "lanes": 2, "rows": 6,
                            "bucket": 8, "n_requests": 3}),
        ],
        counters={"serve.requests": 120, "serve.rows": 240,
                  "serve.batches": 18, "serve.padded_rows": 24,
                  "serve.rejected": 0, "serve.errors": 1,
                  "span.request_wait.count": 18,
                  "span.request_wait.seconds": 0.031,
                  "span.encode.count": 18, "span.encode.seconds": 0.052},
        gauges={"serve.queue_depth": 1, "serve.batch_occupancy": 0.909,
                "serve.latency_p50_ms": 4.1, "serve.latency_p95_ms": 7.9,
                "serve.latency_p99_ms": 14.2},
        hists=hist0,
    )
    hist1 = {"serve.latency_ms": {
        "bounds": _HIST_BOUNDS,
        "counts": [0, 0, 1, 12, 70, 38, 16, 2, 1, 0, 0, 0, 0, 0, 0],
        "sum": 941.0, "count": 140}}
    replica_log(
        "replica1",
        requests=[
            {
                "ts": T + 9.078, "trace_id": TRACE_RETRIED,
                "span_id": "f1f1f1f1f1f1f1f1",
                "parent_span": "a1a1a1a1a1a1a1a1",
                "dict": "d0", "rows": 2, "ts_start": round(T + 9.064, 4),
                "latency_ms": 13.5,
                "phases": {"request_wait": 0.0061, "encode": 0.0072,
                           "dequant": 0.0},
                "bucket": 16, "lanes": 2, "n_requests": 6,
            },
            {
                "ts": T + 12.030, "trace_id": TRACE_TAIL,
                "span_id": "f2f2f2f2f2f2f2f2",
                "parent_span": "c1c1c1c1c1c1c1c1",
                "dict": "d1", "rows": 4, "ts_start": round(T + 12.001, 4),
                "latency_ms": 28.7,
                "phases": {"request_wait": 0.0213, "encode": 0.0071,
                           "dequant": 0.0},
                "bucket": 64, "lanes": 2, "n_requests": 14,
            },
        ],
        batch_spans=[
            (T + 9.0701, 0.0061, "queue",
             [TRACE_RETRIED], {"category": "request_wait", "n_requests": 6,
                               "mean_wait_ms": 4.9}),
            (T + 9.0762, 0.0072, "encode_g2_b16",
             [TRACE_RETRIED], {"category": "encode", "lanes": 2, "rows": 12,
                               "bucket": 16, "n_requests": 6}),
            (T + 12.0223, 0.0213, "queue",
             [TRACE_TAIL], {"category": "request_wait", "n_requests": 14,
                            "mean_wait_ms": 12.4}),
            (T + 12.0294, 0.0071, "encode_g2_b64",
             [TRACE_TAIL], {"category": "encode", "lanes": 2, "rows": 52,
                            "bucket": 64, "n_requests": 14}),
        ],
        counters={"serve.requests": 140, "serve.rows": 290,
                  "serve.batches": 21, "serve.padded_rows": 38,
                  "serve.rejected": 1, "serve.errors": 0,
                  "span.request_wait.count": 21,
                  "span.request_wait.seconds": 0.084,
                  "span.encode.count": 21, "span.encode.seconds": 0.078},
        gauges={"serve.queue_depth": 2, "serve.batch_occupancy": 0.884,
                "serve.latency_p50_ms": 4.6, "serve.latency_p95_ms": 11.3,
                "serve.latency_p99_ms": 26.9},
        hists=hist1,
    )

    # -- SLO configs: one the run satisfies, one it violates -----------------
    slo_ok = {
        "windows": {"fast_burn_seconds": 10.0, "slow_burn_seconds": 60.0},
        "objectives": [
            {"name": "availability", "type": "availability", "target": 0.99},
            {"name": "p99_latency", "type": "latency", "percentile": 0.99,
             "threshold_ms": 50.0},
            {"name": "queue_depth", "type": "queue_depth", "max_depth": 8},
        ],
    }
    with open(TRACED_RUN_DIR / "slo.json", "w") as f:
        json.dump(slo_ok, f, indent=1)
        f.write("\n")
    slo_strict = {
        "windows": {"fast_burn_seconds": 10.0, "slow_burn_seconds": 60.0},
        "objectives": [
            # 4 nines over a run carrying one error in 261: past budget
            {"name": "availability", "type": "availability",
             "target": 0.9999},
            # the merged histogram's p99 bucket is 32 ms: violated at 8
            {"name": "p99_latency", "type": "latency", "percentile": 0.99,
             "threshold_ms": 8.0},
        ],
    }
    with open(TRACED_RUN_DIR / "slo_strict.json", "w") as f:
        json.dump(slo_strict, f, indent=1)
        f.write("\n")
    # -- /metrics exposition golden ------------------------------------------
    # the Prometheus text format is a wire contract (counter/gauge/histogram
    # lines, label escaping, stable sorted ordering): pinned byte-for-byte
    from sparse_coding__tpu.telemetry.metrics_http import render_prometheus

    text = render_prometheus(
        counters={"serve.requests": 120, "serve.errors": 1,
                  "router.retries": 3.5},
        gauges={"serve.queue_depth": 2, "serve.batch_occupancy": 0.909},
        hists={"serve.latency_ms": {
            "bounds": [0.25, 0.5, 1.0],
            "counts": [1, 0, 2, 1],  # last = overflow (> 1.0)
            "sum": 3.85, "count": 4,
        }},
        labels={"replica": 'we"ird\\repl\nica'},  # escaping contract
    )
    (REPO / "tests" / "golden" / "metrics_exposition.txt").write_text(text)
    print(f"Wrote {TRACED_RUN_DIR}/ (router + 2 replicas, slo.json + "
          "slo_strict.json) + tests/golden/metrics_exposition.txt")


FEATURE_RUN_DIR = REPO / "tests" / "golden" / "feature_run"
FEATURE_BASE_TS = 1_754_800_000.0  # fixed: the fixture must regenerate identically


def make_feature_run_fixture():
    """Deterministic dictionary-health fixture (ISSUE 17 satellite): a run
    dir holding REAL ``feature_stats.<gen>.npz`` snapshots (seeded arithmetic
    sketches written through the real `FeatureSnapshot` codec) plus a
    hand-stamped event log with their ``feature_stats`` pointer events —
    pinning, in tier-1, the features CLI's rendering and exit codes, the
    report's "Dictionary health" section, and the monitor's ``features:``
    line (`tests/test_feature_stats.py`).

    The modeled story: a two-member l1 sweep flushes its sketch at two chunk
    boundaries (train0000/train0001 — nearly identical windows), then a
    serve replica flushes one window over the same dictionaries whose
    activation magnitudes have shifted two log-buckets up — the train↔serve
    drift detector must read it as past the 0.25 "major" PSI line while the
    train0000→train0001 pair stays "stable".

    Byte-stability: sketches are pure arithmetic (no RNG), event timestamps
    are hand-stamped, and the npz zip members are re-stamped to the epoch so
    regeneration is diff-clean."""
    import zipfile

    import numpy as np

    from sparse_coding__tpu.telemetry.feature_stats import (
        FeatureStatsConfig,
        drift_report,
        render_features,
        snapshot_aggregates,
        summarize_run,
        write_snapshot,
    )

    FEATURE_RUN_DIR.mkdir(parents=True, exist_ok=True)
    for old in FEATURE_RUN_DIR.glob("feature_stats.*.npz"):
        old.unlink()  # write_snapshot appends past existing generations
    cfg = FeatureStatsConfig()
    F, B = 32, cfg.n_buckets

    def lane(rows, rate_scale, bucket_shift, dead_from):
        """One lane's sketch from pure arithmetic: decaying firing rates
        with a dead tail, triangular bucket profiles (integer counts that
        sum exactly to ``fire``, as the on-device sketch guarantees)."""
        i = np.arange(F, dtype=np.float64)
        rate = np.clip(0.9 - 0.028 * i, 0.0, 1.0) * rate_scale
        rate[dead_from:] = 0.0
        fire = np.floor(rate * rows)
        centre = np.clip(2.0 + (i % 4) + bucket_shift, 0, B - 1)
        b = np.arange(B, dtype=np.float64)
        w = np.maximum(0.0, 2.0 - np.abs(b[None, :] - centre[:, None]))
        w = w / np.maximum(w.sum(axis=1, keepdims=True), 1e-12)
        hist = np.floor(w * fire[:, None])
        hist[np.arange(F), centre.astype(int)] += fire - hist.sum(axis=1)
        mag = cfg.hist_lo * cfg.hist_ratio ** (centre + 0.5)
        return {
            "rows": float(rows),
            "fire": fire,
            "sum": mag * fire,
            "sumsq": mag * mag * fire,
            "max": np.where(fire > 0, mag * 1.5, 0.0),
            "hist": hist,
        }

    def host(lanes):
        return {
            "featstat_rows": np.array([ln["rows"] for ln in lanes]),
            "featstat_fire": np.stack([ln["fire"] for ln in lanes]),
            "featstat_sum": np.stack([ln["sum"] for ln in lanes]),
            "featstat_sumsq": np.stack([ln["sumsq"] for ln in lanes]),
            "featstat_max": np.stack([ln["max"] for ln in lanes]),
            "featstat_hist": np.stack([ln["hist"] for ln in lanes]),
        }

    train_names = ["l1_1.00e-04", "l1_1.00e-03"]
    snap0 = write_snapshot(
        FEATURE_RUN_DIR, "train",
        host([lane(4096, 1.0, 0, 30), lane(4096, 0.6, 0, 28)]),
        train_names, cfg, meta={"step": 64},
    )
    snap1 = write_snapshot(
        FEATURE_RUN_DIR, "train",
        host([lane(4096, 0.98, 0, 30), lane(4096, 0.59, 0, 28)]),
        train_names, cfg, meta={"step": 128},
    )
    # the drifted serve window: magnitudes two log-buckets up, rates moved
    serve_snap = write_snapshot(
        FEATURE_RUN_DIR, "serve",
        host([lane(2048, 0.7, 2, 30), lane(2048, 0.85, 2, 28)]),
        ["d0", "d1"], cfg, meta={"replica": "replica0"},
    )

    def restamp(path):
        with zipfile.ZipFile(path) as z:
            members = [(zi.filename, z.read(zi.filename)) for zi in z.infolist()]
        with zipfile.ZipFile(path, "w", zipfile.ZIP_DEFLATED) as z:
            for name, data in members:
                zi = zipfile.ZipInfo(name, date_time=(1980, 1, 1, 0, 0, 0))
                zi.compress_type = zipfile.ZIP_DEFLATED
                z.writestr(zi, data)

    for p in sorted(FEATURE_RUN_DIR.glob("feature_stats.*.npz")):
        restamp(p)

    drift = drift_report(snap1, serve_snap)
    assert drift is not None and drift["score"] > 0.25, drift
    stable = drift_report(snap0, snap1)
    assert stable is not None and stable["score"] < 0.1, stable

    # -- event log: the pointer events a real run would have emitted --------
    T = FEATURE_BASE_TS
    seq = 0

    def rec(ts, event, **fields):
        nonlocal seq
        seq += 1
        return {"seq": seq, "ts": round(ts, 3), "event": event, **fields}

    def span_rec(ts_start, seconds, category, name, **fields):
        return rec(ts_start + seconds, "span", category=category, name=name,
                   ts_start=round(ts_start, 3), seconds=seconds, **fields)

    def flush_rec(ts, snap, drift_rep, **extra):
        agg = snapshot_aggregates(snap)
        fields = {
            "scope": snap.scope, "gen": snap.gen,
            "path": snap.meta.get("path", ""), "names": list(snap.names),
            "n_feats": snap.n_feats,
            **{k: round(v, 6) for k, v in agg.items()},
        }
        if drift_rep is not None:
            fields["drift_score"] = round(drift_rep["score"], 6)
            fields["drift_method"] = drift_rep["method"]
            fields["drift_top"] = [
                [f, round(d, 6)] for f, d in drift_rep["top"]
            ]
        fields.update(extra)
        return rec(ts, "feature_stats", **fields)

    fp = {"python": "3.11.8", "jax": "0.6.0", "backend": "cpu",
          "device_kind": "golden-cpu", "device_count": 1, "git_sha": "g0lden"}
    agg_t = snapshot_aggregates(snap1)
    agg_s = snapshot_aggregates(serve_snap)
    events = [
        rec(T, "run_start", run_name="feature_golden", generation=0,
            config={"batch": 512, "l1_values": [1e-4, 1e-3],
                    "feature_stats": True},
            fingerprint=fp),
        rec(T + 2.0, "compile", name="ensemble.step_scan", seconds=1.8),
        rec(T + 2.1, "chunk_start", chunk=0, position=0),
        rec(T + 5.1, "chunk_end", chunk=0, position=0, seconds=3.0, steps=64),
        span_rec(T + 5.1, 0.02, "feature_flush", "train"),
        flush_rec(T + 5.13, snap0, None, step=64),
        rec(T + 5.2, "chunk_start", chunk=1, position=1),
        rec(T + 8.2, "chunk_end", chunk=1, position=1, seconds=3.0, steps=64),
        span_rec(T + 8.2, 0.02, "feature_flush", "train"),
        flush_rec(T + 8.23, snap1, None, step=128),
        # the serve tier's flush against the train baseline, same run dir
        span_rec(T + 12.0, 0.03, "feature_flush", "serve"),
        flush_rec(T + 12.04, serve_snap, drift, replica="replica0"),
        rec(T + 14.0, "snapshot",
            counters={"chunks": 2, "train.steps": 128,
                      "train.feature.flushes": 2, "serve.feature.flushes": 1,
                      "span.feature_flush.count": 3,
                      "span.feature_flush.seconds": 0.07},
            gauges={"train.feature.dead_frac": round(agg_t["dead_frac"], 6),
                    "train.feature.gini": round(agg_t["gini"], 6),
                    "train.feature.hot_frac": round(agg_t["hot_frac"], 6),
                    "serve.feature.dead_frac": round(agg_s["dead_frac"], 6),
                    "serve.feature.gini": round(agg_s["gini"], 6),
                    "serve.feature.hot_frac": round(agg_s["hot_frac"], 6),
                    "serve.feature.drift_score": round(drift["score"], 6)}),
        rec(T + 14.5, "run_end", status="ok", generation=0, steps=128,
            wall_seconds=14.5),
    ]
    with open(FEATURE_RUN_DIR / "events.jsonl", "w") as f:
        for e in events:
            f.write(json.dumps(e) + "\n")

    # the CLI rendering pin: regenerated from the real pipeline with the
    # run_dir normalized to the repo-relative form the test uses
    info = summarize_run(FEATURE_RUN_DIR)
    info["run_dir"] = "tests/golden/feature_run"
    (FEATURE_RUN_DIR / "expected_cli.txt").write_text(render_features(info))
    print(f"Wrote {FEATURE_RUN_DIR}/ (3 npz snapshots + events.jsonl + "
          f"expected_cli.txt; drift {drift['score']:.3f}, "
          f"control {stable['score']:.3f})")


TOWER_RUN_DIR = REPO / "tests" / "golden" / "tower_run"
TOWER_BASE_TS = 1_754_700_000.0  # fixed: the fixture must regenerate identically


def make_tower_run_fixture():
    """Deterministic control-tower fixture (ISSUE 18): a hand-stamped tower
    state dir pinning the full observability chain — ``series.jsonl`` poll
    snapshots, the pending→firing→resolved transitions in ``alerts.jsonl``
    (driven through the REAL `AlertManager` state machine at fixed
    timestamps), the ``incidents/INC-0001.json`` correlation record (built
    by the real `Tower._incident_context` over hand-seeded replica
    transitions / traces / spans), the ``state.json`` pool snapshot, and
    the ``tower check`` exit codes.

    Hand-stamped, not a real run — golden fixtures must be byte-stable.
    The shape: 2 serve replicas behind a router, 6 polls at 5 s. replica1
    dies between polls 1 and 2 (``router.live_replicas`` 2→1), the
    ``replicas-live`` gauge_min rule goes pending at poll 2, fires at
    poll 4 (``for: 6 s`` held), and resolves at poll 5 after the
    supervisor restart brings the gauge back to 2. The latency histogram
    carries 3 slow observations so the ``serve.latency`` slow-burn rate —
    the number `evaluate_scrape` can never produce — pins non-None."""
    import shutil

    from sparse_coding__tpu.telemetry.tower import Tower, load_rules

    if TOWER_RUN_DIR.exists():
        shutil.rmtree(TOWER_RUN_DIR)  # alerts.jsonl appends: start clean
    TOWER_RUN_DIR.mkdir(parents=True)
    T = TOWER_BASE_TS

    rules_doc = {
        "windows": {"fast_burn_seconds": 300.0, "slow_burn_seconds": 3600.0},
        "rules": [
            {"name": "replicas-live", "for_seconds": 6.0, "severity": "page",
             "objective": {"type": "gauge_min",
                           "gauge": "router.live_replicas", "min_value": 2}},
            {"name": "availability", "for_seconds": 10.0, "severity": "page",
             "objective": {"type": "availability", "target": 0.999}},
            {"name": "p99", "for_seconds": 10.0, "severity": "ticket",
             "objective": {"type": "latency", "percentile": 0.99,
                           "threshold_ms": 50.0}},
        ],
    }
    with open(TOWER_RUN_DIR / "alerts.json", "w") as f:
        json.dump(rules_doc, f, indent=1)
    # the static estate description the CLI's --config consumes, schema-
    # pinned alongside the state it produced
    with open(TOWER_RUN_DIR / "tower.json", "w") as f:
        json.dump({
            "targets": [{"url": "http://127.0.0.1:8701", "label": "router"}],
            "replicasets": ["runs/tier"],
            "run_dirs": ["runs/tier"],
            "interval_seconds": 5.0,
            "rules": "alerts.json",
        }, f, indent=1)

    bounds = [1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0]
    live = [2, 2, 1, 1, 1, 2]
    queue = [0, 1, 2, 3, 2, 0]
    bad_cum = [0, 0, 1, 1, 2, 3]  # slow (>50 ms) observations, cumulative
    records = []
    for i in range(6):
        req = 100.0 + 60.0 * i
        n = i + 1
        counts = [20.0 * n, 25.0 * n, 10.0 * n, 5.0 * n, 0.0, 0.0,
                  float(bad_cum[i]), 0.0]
        hist = {"bounds": bounds, "counts": counts,
                "sum": round(180.0 * n + 60.0 * bad_cum[i], 1),
                "count": 60.0 * n + bad_cum[i]}
        r1_up = i not in (2, 3, 4)
        counters = {"serve.requests": req, "serve.errors": 0.0,
                    "router.requests": req,
                    "replica0::serve.requests": req / 2}
        if r1_up:
            counters["replica1::serve.requests"] = req / 2
        gauges = {"router.live_replicas": float(live[i]),
                  "router.replicas": 2.0,
                  "serve.queue_depth": float(queue[i]),
                  "replica0::serve.queue_depth": float(queue[i]),
                  "fleet.idle_workers": 2.0, "fleet.busy_workers": 1.0,
                  "fleet.pending_items": float(4 - i if i < 4 else 0),
                  "fleet.leased_items": 1.0,
                  "train.goodput_frac": 0.88}
        targets = {
            "replica0": {"up": True, "url": "http://127.0.0.1:8702",
                         "kind": "serve"},
            "replica1": (
                {"up": True, "url": "http://127.0.0.1:8703", "kind": "serve"}
                if r1_up else
                {"up": False, "url": "http://127.0.0.1:8703",
                 "error": "URLError"}
            ),
            "router": {"up": True, "url": "http://127.0.0.1:8701",
                       "kind": "router"},
        }
        from sparse_coding__tpu.telemetry.metrics_http import sanitize_key
        records.append({
            "ts": round(T + 5.0 * i, 6),
            "counters": {sanitize_key(k): v for k, v in sorted(counters.items())},
            "gauges": {sanitize_key(k): v for k, v in sorted(gauges.items())},
            "hists": {sanitize_key("serve.latency_ms"): hist},
            "targets": targets,
        })

    class _NullTel:  # the fixture pins files, not the tower's own telemetry
        def counter_inc(self, *a, **k): pass
        def gauge_set(self, *a, **k): pass
        def event(self, *a, **k): pass
        def close(self, *a, **k): pass

    rules_cfg = load_rules(rules_doc)
    tower = Tower(TOWER_RUN_DIR, rules=rules_cfg["rules"],
                  windows=rules_cfg["windows"], interval=5.0,
                  telemetry=_NullTel(), resume=False)
    # hand-seeded correlation state, shaped exactly like Tower._ingest_event
    # leaves it after tailing the router/replica logs of this story
    tower.replica_states = {"replica0": "live", "replica1": "dead"}
    tower.replica_transitions.extend([
        {"ts": round(T + 9.2, 3), "replica": "replica1", "from": "live",
         "to": "suspect", "reason": "conn_refused"},
        {"ts": round(T + 11.7, 3), "replica": "replica1", "from": "suspect",
         "to": "dead", "reason": "health_timeout"},
    ])
    tower.anomalies.append({"ts": round(T + 11.9, 3), "event": "anomaly",
                            "kind": "replica_dead", "replica": "replica1"})
    for j, lat in enumerate((61.4, 58.9, 22.0, 14.1, 9.8, 7.2)):
        tower.traces.append({
            "ts": round(T + 8.0 + 0.5 * j, 3),
            "trace_id": f"{0xa3f2c0de + j:08x}{'00' * 12}",
            "latency_ms": lat, "replica": "replica0", "dict": "d0",
        })
    tower.span_seconds = {"step": 90.0, "compile": 2.0, "data_wait": 8.0}

    transitions = []
    with open(TOWER_RUN_DIR / "series.jsonl", "w") as f:
        for rec in records:
            f.write(json.dumps(rec) + "\n")
            tower.store.ingest(rec)
            tower.target_status = rec["targets"]
            tower.polls += 1
            tower.last_poll_ts = rec["ts"]
            transitions.extend(tower.alerts.evaluate(tower.store, rec["ts"]))
    tower._write_state(records[-1]["ts"])

    seq = [(t["rule"], t["from"], t["to"]) for t in transitions]
    assert seq == [
        ("replicas-live", "inactive", "pending"),
        ("replicas-live", "pending", "firing"),
        ("replicas-live", "firing", "resolved"),
    ], f"fixture alert story drifted: {seq}"
    assert (TOWER_RUN_DIR / "incidents" / "INC-0001.json").is_file()
    print(f"Wrote {TOWER_RUN_DIR}/ (series.jsonl x{len(records)}, "
          f"alerts.json(l), incidents/INC-0001.json, state.json, tower.json)")


LINEAGE_RUN_DIR = REPO / "tests" / "golden" / "lineage_run"
LINEAGE_BASE_TS = 1_754_800_000.0  # fixed: the fixture must regenerate identically
LINEAGE_TRACE = "feed5eedfeed5eedfeed5eedfeed5eed"  # fixed, readable trace id


def make_lineage_run_fixture():
    """Deterministic LEGACY provenance tree (ISSUE 19): store + run +
    serve dirs whose manifests and events predate the ``provenance``
    event vocabulary — the graph must reconstruct the full chain
    (traced response → serve generation → dict → export → checkpoint →
    training run → chunk store → harvest config) from committed
    manifests alone. Everything is hand-stamped / re-stamped to
    LINEAGE_BASE_TS so the tree is byte-stable; the pinned
    ``expected_*`` files capture `lineage explain/blast/check` stdout,
    which `tests/test_lineage.py` re-runs byte-for-byte in tier-1."""
    import contextlib
    import io
    import json as _json
    import shutil

    import numpy as np

    from sparse_coding__tpu.data import integrity
    from sparse_coding__tpu.data.chunks import save_chunk
    from sparse_coding__tpu.telemetry import provenance
    from sparse_coding__tpu.utils.manifest import write_manifest

    if LINEAGE_RUN_DIR.exists():
        shutil.rmtree(LINEAGE_RUN_DIR)
    t = LINEAGE_BASE_TS

    # -- store/: three real committed chunks + the harvest cursor ----------
    store = LINEAGE_RUN_DIR / "store"
    store.mkdir(parents=True)
    rng = np.random.default_rng(19)
    harvest_config = {
        "model_name": "pythia-70m", "layers": [2], "locations": ["residual"],
        "activation_width": 64, "chunk_size": 64, "center_dataset": False,
    }
    harvest_sha = provenance.config_digest(harvest_config)
    for i in range(3):
        save_chunk(store, i, rng.standard_normal((64, 16)).astype(np.float32))
        mp = integrity.chunk_manifest_path(store, i)
        man = _json.loads(mp.read_text())
        man["created_at"] = t
        mp.write_text(_json.dumps(man))
    integrity.write_json_atomic(store / "sc_harvest_cursor.json", {
        "format": 1, "chunk": 3, "batch_cursor": 0,
        "config_sha": harvest_sha, "updated_at": t,
    })

    # -- run/: events + a committed checkpoint + a LEGACY export -----------
    run = LINEAGE_RUN_DIR / "run"
    ckpt = run / "ckpt_0"
    ckpt.mkdir(parents=True)
    (ckpt / "tree.npz").write_bytes(b"golden-lineage-checkpoint-tree-v1\n")
    write_manifest(ckpt / "sc_manifest.json", {"tree.npz": ckpt / "tree.npz"},
                   extra={"epoch": 0, "position": 3})
    pkl = run / "learned_dicts.pkl"
    pkl.write_bytes(b"golden-lineage-export-pkl-v1\n")
    # legacy sidecar: digests only, NO producer-identity block
    write_manifest(pkl.with_name(pkl.name + ".manifest.json"),
                   {pkl.name: pkl})
    for mp in (ckpt / "sc_manifest.json",
               pkl.with_name(pkl.name + ".manifest.json")):
        man = _json.loads(mp.read_text())
        man["created_at"] = t
        mp.write_text(_json.dumps(man))

    seq = 0
    ts = t

    def rec(event, dt=1.0, **fields):
        nonlocal seq, ts
        seq += 1
        ts += dt
        return {"seq": seq, "ts": round(ts, 3), "event": event, **fields}

    fingerprint = {"python": "3.11.8", "jax": "0.6.0", "backend": "cpu",
                   "device_kind": "golden-cpu", "device_count": 1,
                   "git_sha": "g0lden"}
    train_events = [
        rec("run_start", run_name="lineage_train",
            config={"dataset_folder": "../store", "l1_values": [1e-3],
                    "outer_epochs": 1},
            fingerprint=fingerprint),
        rec("resume", checkpoint="ckpt_0",
            cursor={"chunk": 1, "epoch": 0, "position": 1}),
        rec("run_end", dt=40.0, status="ok", steps=24, wall_seconds=41.0),
    ]
    with open(run / "events.jsonl", "w") as f:
        for e in train_events:
            f.write(_json.dumps(e) + "\n")

    # -- serve/: legacy registry events (no generation field) + a trace ----
    serve = LINEAGE_RUN_DIR / "serve"
    serve.mkdir(parents=True)
    seq, ts = 0, t + 100.0
    serve_events = [
        rec("run_start", run_name="lineage_serve", config={"port": 0},
            fingerprint=fingerprint),
        rec("serve_dict_added", dict="d0",
            source="../run/learned_dicts.pkl", weights=1.0),
        rec("request_trace", dt=2.0, trace_id=LINEAGE_TRACE, dict="d0",
            ts_start=ts + 2.0, latency_ms=4.2, status=200),
        rec("run_end", dt=1.0, status="ok"),
    ]
    with open(serve / "events.jsonl", "w") as f:
        for e in serve_events:
            f.write(_json.dumps(e) + "\n")

    # -- pin the CLI outputs (what tier-1 re-runs byte-for-byte) -----------
    def capture(argv):
        buf = io.StringIO()
        with contextlib.redirect_stdout(buf):
            code = provenance.main(argv)
        return code, buf.getvalue()

    root = str(LINEAGE_RUN_DIR)
    pins = {
        "expected_explain.md": (0, ["explain", LINEAGE_TRACE, root]),
        "expected_blast.md": (0, ["blast", "chunk:store#0", root]),
        "expected_check.txt": (0, ["check", root]),
    }
    for name, (want_code, argv) in pins.items():
        code, out = capture(argv)
        assert code == want_code, f"{argv}: exit {code} != {want_code}\n{out}"
        (LINEAGE_RUN_DIR / name).write_text(out)

    # the explain chain must reach every layer from the trace id alone
    explain = (LINEAGE_RUN_DIR / "expected_explain.md").read_text()
    for needle in (f"response:{LINEAGE_TRACE}", "generation:serve#1",
                   "dict:serve#d0", "export:run/learned_dicts.pkl",
                   "checkpoint:run/ckpt_0", "run:run", "store:store",
                   "chunk:store#0", f"harvest:{harvest_sha}"):
        assert needle in explain, f"explain chain missing {needle}"
    print(f"Wrote {LINEAGE_RUN_DIR}/ (store x3 chunks, run + ckpt_0 + "
          "legacy export, serve events, expected_explain/blast/check pins)")


def main():
    if "--lineage-run" in sys.argv:
        make_lineage_run_fixture()
        return
    if "--tower-run" in sys.argv:
        make_tower_run_fixture()
        return
    if "--traced-run" in sys.argv:
        make_traced_run_fixture()
        return
    if "--pod-run" in sys.argv:
        make_pod_run_fixture()
        return
    if "--corrupt-store" in sys.argv:
        make_corrupt_store_fixture()
        return
    if "--fleet-run" in sys.argv:
        make_fleet_run_fixture()
        return
    if "--resumed-run" in sys.argv:
        make_resumed_run_fixture()
        return
    if "--goodput-run" in sys.argv:
        make_goodput_run_fixture()
        return
    if "--serve-run" in sys.argv:
        make_serve_run_fixture()
        return
    if "--router-run" in sys.argv:
        make_router_run_fixture()
        return
    if "--bench-fixture" in sys.argv:
        make_bench_fixture()
        return
    if "--feature-run" in sys.argv:
        make_feature_run_fixture()
        return
    # CPU: the fixture must evaluate identically on any dev machine / CI
    os.environ.setdefault("XLA_FLAGS", "")
    import jax

    jax.config.update("jax_platforms", "cpu")

    from sparse_coding__tpu.train.checkpoint import save_learned_dicts

    ens, eval_batch, truth, traj = train_fixture_ensemble()
    dicts = ens.to_learned_dicts()
    metrics = fixture_metrics(dicts, eval_batch, truth)

    GOLDEN_DIR.mkdir(parents=True, exist_ok=True)
    save_learned_dicts(
        GOLDEN_DIR / "learned_dicts.pkl",
        [(ld, {"l1_alpha": a}) for ld, a in zip(dicts, L1_GRID)],
    )
    golden = {
        "what": (
            "smoke-scale BASELINE-config-2 tied-SAE l1 sweep trained to FVU "
            "plateau on seeded synthetic data with planted ground truth; "
            "regenerate ONLY via scripts/make_golden_fixture.py"
        ),
        "config": {
            "d_act": D_ACT, "n_dict": N_DICT, "l1_grid": list(L1_GRID),
            "batch": BATCH, "steps_per_epoch": STEPS_PER_EPOCH,
            "plateau_tol": PLATEAU_TOL, "seed": SEED,
        },
        "epochs_run": len(traj),
        "fvu_trajectory": traj,
        "members": metrics,
        "tolerances": {
            # committed dicts re-evaluated on regenerated data: only numeric
            # drift (XLA version) — tight
            "reeval_fvu_rtol": 0.02,
            "reeval_l0_rtol": 0.05,
            # from-scratch retrain: optimizer/compiler drift — loose but
            # regression-meaningful
            "retrain_fvu_rtol": 0.15,
            "retrain_l0_rtol": 0.30,
            "retrain_mmcs_to_committed_min": 0.85,
        },
    }
    with open(GOLDEN_DIR / "golden.json", "w") as f:
        json.dump(golden, f, indent=1)
    print(json.dumps(golden["members"], indent=1))
    print(f"Wrote {GOLDEN_DIR}/learned_dicts.pkl + golden.json "
          f"({(GOLDEN_DIR / 'learned_dicts.pkl').stat().st_size / 1e6:.2f} MB)")


if __name__ == "__main__":
    main()
