"""Fused harvest→train streaming example.

Trains an l1-sweep SAE ensemble directly on LM activations as they are
captured — the chunks never leave HBM (`data.harvest_to_device`,
THROUGHPUT.md round-2f). Use this shape when the activations are consumed
once by training on the same chip(s); use `make_activation_dataset` +
`train.sweep` when you need the on-disk store (resume, multiple epochs over
more data than fits in HBM, offline eval).

Runs on CPU or one TPU chip in ~a minute with a small random-init subject
model: `python examples/streaming_sweep_example.py`
"""

import sys
from pathlib import Path

import numpy as np

REPO = Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

import jax
import jax.numpy as jnp

from sparse_coding__tpu import build_ensemble, metrics as sm
from sparse_coding__tpu.data import harvest_to_device
from sparse_coding__tpu.lm import LMConfig, init_params
from sparse_coding__tpu.models import FunctionalTiedSAE


def main():
    # subject model: pythia-70m-like geometry at random init (swap in
    # lm.convert.load_model("EleutherAI/pythia-70m-deduped") with weights)
    layer, loc = 2, "residual"
    cfg = LMConfig(
        arch="neox", n_layers=4, d_model=128, n_heads=4, d_mlp=512,
        vocab_size=1024, n_ctx=64, rotary_pct=0.25,
    )
    params = init_params(jax.random.PRNGKey(0), cfg)
    tokens = np.random.default_rng(0).integers(0, cfg.vocab_size, (512, 64), dtype=np.int32)

    ens = build_ensemble(
        FunctionalTiedSAE,
        jax.random.PRNGKey(1),
        [{"l1_alpha": a} for a in (1e-4, 3e-4, 1e-3, 3e-3)],
        optimizer_kwargs={"learning_rate": 1e-3},
        activation_size=cfg.d_model,
        n_dict_components=4 * cfg.d_model,
    )

    batch_size, n_epochs_per_chunk = 1024, 4
    last_chunk = None
    for i, chunk in enumerate(
        harvest_to_device(
            params, cfg, tokens, [layer], [loc],
            batch_size=64, chunk_size_gb=64 * 64 * cfg.d_model * 2 * 2 / 1024**3,
        )
    ):
        acts = chunk[(layer, loc)].astype(jnp.float32)  # HBM-resident already
        key = jax.random.PRNGKey(10 + i)
        for _ in range(n_epochs_per_chunk):
            key, k = jax.random.split(key)
            perm = jax.random.permutation(k, acts.shape[0])
            n_steps = acts.shape[0] // batch_size
            batches = acts[perm[: n_steps * batch_size]].reshape(
                n_steps, batch_size, cfg.d_model
            )
            losses = ens.step_scan(batches)  # one dispatch per epoch pass
        loss = np.asarray(jax.device_get(losses["loss"]))[-1]
        print(f"chunk {i}: rows={acts.shape[0]} final losses {np.round(loss, 5)}")
        last_chunk = acts

    rows = sm.evaluate_dicts(ens.to_learned_dicts(), last_chunk)
    for hp, row in zip((1e-4, 3e-4, 1e-3, 3e-3), rows):
        print(f"l1={hp:.0e}  fvu={row['fvu']:.3f}  l0={row['l0']:.1f}")


if __name__ == "__main__":
    main()
