"""On-device per-model training-health pack.

The signals every hand-run failure study needed (LR_COLLAPSE_r03: silent
all-zero-code collapse; RESURRECT_r04: dead-feature fractions), computed
INSIDE the jitted ensemble step so they cost one fused reduction each and
ride the `MetricLogger` device-scalar buffer — the no-per-step-host-sync
invariant holds (the host first sees them at `flush()`).

Per model (``[n_models]``-shaped step outputs, prefixed ``health_``):
  - ``health_grad_norm``   global L2 norm of this member's gradient pytree
  - ``health_dict_norm``   mean L2 row norm of the dictionary param
                           ("decoder" when present, else "encoder" — the
                           tied families store the dictionary there)
  - ``health_nonfinite``   1.0 when this member's total loss is NaN/Inf
  - ``health_dead_frac``   fraction of features whose bias-corrected firing
                           EMA is at/below `dead_threshold` — the live
                           counterpart of the resurrect study's `c_totals`

The firing EMA persists in the ensemble buffers under `FIRE_EMA_KEY`
([n_models, n_feats]); it is checkpointed with the rest of the state, so
resume keeps the dead-feature estimate. Signatures whose aux carries no code
tensor ``"c"`` get ``health_dead_frac = NaN`` and an untouched EMA.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

__all__ = ["HealthConfig", "FIRE_EMA_KEY", "health_pack", "init_fire_ema", "n_feats_of"]

FIRE_EMA_KEY = "health_fire_ema"


@dataclasses.dataclass(frozen=True)
class HealthConfig:
    """Knobs for the health pack (hashable: part of the shared-step cache key).

    ``ema_decay``: per-step decay of the firing-frequency EMA (0.99 ≈ a
    ~100-step window). ``dead_threshold``: a feature is "dead" when its
    bias-corrected firing frequency is <= this (0.0 = literally never fired
    within the EMA window's resolution; the resurrect study's criterion was
    `c_totals == 0`)."""

    ema_decay: float = 0.99
    dead_threshold: float = 1e-6


def n_feats_of(params) -> int:
    """Dictionary-feature count of one (unstacked) param pytree."""
    for key in ("encoder", "decoder"):
        if key in params:
            return int(params[key].shape[0])
    raise ValueError(
        f"health pack needs an 'encoder' or 'decoder' param to size the "
        f"firing EMA; got keys {sorted(params)}"
    )


def init_fire_ema(n_models: int, n_feats: int) -> jax.Array:
    return jnp.zeros((n_models, n_feats), jnp.float32)


def _global_norm(tree) -> jax.Array:
    leaves = jax.tree.leaves(tree)
    return jnp.sqrt(
        sum(jnp.sum(jnp.square(l.astype(jnp.float32))) for l in leaves)
    )


def health_pack(params, grads, loss, aux, fire_ema, step, cfg: HealthConfig):
    """Per-model health scalars (called INSIDE the vmapped step body).

    Args are one member's slices: `params`/`grads` pytrees, `loss` the total
    scalar, `aux` the signature's aux dict (code tensor under "c" when the
    family exposes one), `fire_ema` this member's [n_feats] EMA row, `step`
    the shared (traced) step counter. Returns ``(metrics, new_fire_ema)``
    with every metric a 0-d f32 — vmap stacks them to [n_models].
    """
    dict_param = params["decoder"] if "decoder" in params else params["encoder"]
    metrics = {
        "health_grad_norm": _global_norm(grads),
        "health_dict_norm": jnp.linalg.norm(
            dict_param.astype(jnp.float32), axis=-1
        ).mean(),
        "health_nonfinite": jnp.where(jnp.isfinite(loss), 0.0, 1.0),
    }
    c = aux.get("c") if isinstance(aux, dict) else None
    if c is None:
        new_ema = fire_ema
        metrics["health_dead_frac"] = jnp.full((), jnp.nan, jnp.float32)
    else:
        fire = (c != 0).mean(axis=0).astype(jnp.float32)  # [n_feats]
        new_ema = cfg.ema_decay * fire_ema + (1.0 - cfg.ema_decay) * fire
        # Adam-style bias correction: an EMA started at zero under-reports
        # firing for the first ~1/(1-decay) steps, which would fake a
        # high-then-falling dead fraction at run start
        bias = 1.0 - cfg.ema_decay ** (step.astype(jnp.float32) + 1.0)
        ema_hat = new_ema / jnp.maximum(bias, 1e-12)
        metrics["health_dead_frac"] = (ema_hat <= cfg.dead_threshold).mean().astype(
            jnp.float32
        )
    return metrics, new_ema
