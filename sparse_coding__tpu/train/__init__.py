from sparse_coding__tpu.train.loop import (
    DriverCheckpointer,
    ensemble_train_loop,
    make_fista_decoder_update,
)
from sparse_coding__tpu.train.preemption import (
    RESUMABLE_EXIT_CODE,
    Preempted,
    install_signal_handlers,
    pod_agree_preempt,
    preemption_requested,
    request_preemption,
    resume_requested,
)
from sparse_coding__tpu.train.sweep import (
    filter_learned_dicts,
    format_hyperparam_val,
    init_model_dataset,
    init_synthetic_dataset,
    log_sweep_metrics,
    sweep,
    unstacked_to_learned_dicts,
)
from sparse_coding__tpu.train.checkpoint import (
    gc_checkpoints,
    latest_checkpoint,
    load_learned_dicts,
    restore_ensemble_checkpoint,
    save_checkpoint_tree,
    save_ensemble_checkpoint,
    save_learned_dicts,
    verify_checkpoint,
)
from sparse_coding__tpu.train.baselines import (
    load_baseline,
    run_all_baselines,
    run_layer_baselines,
)
from sparse_coding__tpu.train.big_batch import (
    BigBatchState,
    WorstExamples,
    resurrect_dead_features,
    train_big_batch,
)
from sparse_coding__tpu.train.basic_l1_sweep import basic_l1_sweep
from sparse_coding__tpu.train import experiments
from sparse_coding__tpu.train.toy_models import ToySAE, run_single_go, run_toy_grid
