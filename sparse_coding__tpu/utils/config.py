"""Validated config system: dataclasses with auto-generated CLI flags.

Counterpart of the reference `config.py`, with the drift bugs fixed
(SURVEY.md §2.7):
  - `TrainArgs` actually declares every field `sweep()` reads —
    `n_repetitions` and `center_activations` exist here, so entry points
    don't crash with AttributeError (`big_sweep.py:394,402` vs
    `config.py:29-58`).
  - CLI parsing is explicit (`from_cli()`), not a side effect of
    construction — the reference's `__post_init__` parses `sys.argv` on every
    instantiation (`config.py:14-21`), which breaks library/test use.
  - `as_dict()`/`save_yaml()` replace the reference's `dict(cfg)` calls that
    only work on dict-like configs (`big_sweep.py:359,427`).

Every field becomes `--field`; unknown flags raise; overrides print themselves
(parity with `config.py:7-27`).
"""

from __future__ import annotations

import argparse
import dataclasses
from dataclasses import dataclass, field, fields
from pathlib import Path
from typing import Any, Dict, Optional

import jax.numpy as jnp

DTYPES = {
    "float32": jnp.float32,
    "bfloat16": jnp.bfloat16,
    "float16": jnp.float16,
    "float64": jnp.float64,
}


def _resolve_type(hint):
    """Unwrap Optional[T] / string annotations to a concrete type."""
    import typing

    origin = typing.get_origin(hint)
    if origin is typing.Union:
        args = [a for a in typing.get_args(hint) if a is not type(None)]
        return args[0] if args else str
    return hint


def _cli_type(hint, default):
    """Parser for a CLI flag. `hint` is the *resolved* annotation type —
    required because `from __future__ import annotations` turns `f.type` into
    a string, and Optional fields have `default=None` (so `type(default)`
    would parse everything as str)."""
    t = _resolve_type(hint)
    if t is bool or isinstance(default, bool):
        # accept "true"/"false"/"1"/"0"
        return lambda s: s.lower() in ("1", "true", "yes")
    if isinstance(t, type) and t is not type(None):
        return t
    if default is not None:
        return type(default)
    return str


@dataclass
class BaseArgs:
    """Base: validation + explicit CLI overlay + (de)serialization."""

    def __post_init__(self):
        self.validate()

    def validate(self):
        """Hook for subclass invariants; called at construction and after
        CLI/update overlays."""

    # -- CLI -----------------------------------------------------------------

    @classmethod
    def from_cli(cls, argv: Optional[list] = None, **overrides) -> "BaseArgs":
        """Build from defaults + keyword overrides + command-line flags."""
        import typing

        self = cls(**overrides)
        hints = typing.get_type_hints(cls)
        parser = argparse.ArgumentParser(description=cls.__name__)
        for f in fields(self):
            default = getattr(self, f.name)
            parser.add_argument(
                f"--{f.name}", type=_cli_type(hints[f.name], default), default=None
            )
        args = parser.parse_args(argv)
        self.update(args)
        return self

    def update(self, args: Any):
        """Overlay non-None attributes (reference `BaseArgs.update`,
        `config.py:23-27`)."""
        src = vars(args) if not isinstance(args, dict) else args
        unknown = set(src) - {f.name for f in fields(self)}
        if unknown:
            raise ValueError(f"Unknown arguments: {unknown}")
        for key, value in src.items():
            if value is not None:
                print(f"From command line, setting {key} to {value}")
                setattr(self, key, value)
        self.validate()

    # -- (de)serialization ---------------------------------------------------

    def as_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)

    def save_yaml(self, path):
        import yaml

        Path(path).parent.mkdir(parents=True, exist_ok=True)
        with open(path, "w") as f:
            yaml.safe_dump(self.as_dict(), f, sort_keys=True)

    @classmethod
    def load_yaml(cls, path) -> "BaseArgs":
        import yaml

        with open(path) as f:
            return cls(**yaml.safe_load(f))

    @property
    def jnp_dtype(self):
        return DTYPES[getattr(self, "dtype", "float32")]


@dataclass
class TrainArgs(BaseArgs):
    """Sweep/training config (reference `TrainArgs`, `config.py:29-51`)."""

    layer: int = 2
    layer_loc: str = "residual"
    model_name: str = "EleutherAI/pythia-70m-deduped"
    dataset_name: str = "openwebtext"
    dataset_folder: str = ""
    tied_ae: bool = False
    seed: int = 0
    learned_dict_ratio: float = 1.0
    output_folder: str = "outputs"
    dtype: str = "float32"
    center_dataset: bool = False
    n_chunks: int = 30
    chunk_size_gb: float = 2.0
    batch_size: int = 256
    use_wandb: bool = False
    wandb_images: bool = False
    lr: float = 1e-3
    l1_alpha: float = 1e-3
    save_every: int = 5
    n_epochs: int = 1
    # fields sweep() reads that the reference forgot to declare (§2.7):
    n_repetitions: Optional[int] = None  # None → use n_epochs
    center_activations: bool = False
    # bf16 subject forward for the harvest (data.activations._jitted_capture)
    harvest_compute_dtype: Optional[str] = None
    # chunk store format: "float16" (reference contract), "int8" (half the
    # disk/transfer bytes) or "int4" (a quarter); per-row absmax, on-device
    # dequant — data.chunks
    harvest_store_dtype: str = "float16"
    # multi-epoch sweeps with HBM-sized datasets: upload chunks once, not
    # once per epoch (train/sweep.py)
    hbm_cache_chunks: bool = False
    # > 0: ramp every member's l1_alpha linearly from ~0 over this many steps
    # (ensemble.make_ensemble_step). Prevents the early-training feature
    # collapse the l1 x Adam-lr dynamic causes at high l1 (LR_COLLAPSE_r03);
    # measured to cut dead features at zero FVU cost (RESURRECT_r04_warmup*)
    l1_warmup_steps: int = 0

    def validate(self):
        if self.dtype not in DTYPES:
            raise ValueError(f"dtype must be one of {sorted(DTYPES)}, got {self.dtype}")
        if self.harvest_compute_dtype is not None and self.harvest_compute_dtype not in DTYPES:
            raise ValueError(
                f"harvest_compute_dtype must be one of {sorted(DTYPES)} or None, "
                f"got {self.harvest_compute_dtype}"
            )
        if self.harvest_store_dtype not in ("float16", "int8", "int4"):
            raise ValueError(
                f"harvest_store_dtype must be 'float16', 'int8' or 'int4', "
                f"got {self.harvest_store_dtype}"
            )
        # exactly the surface lm.model.make_tensor_name resolves: HOOK_TEMPLATES
        # shorthands (residual/mlp/attn_out/mlp_pre/...), `{layer}`-templated
        # names, and fully-qualified hook names (ADVICE r3: the old list
        # lagged behind the generic-capture surface)
        from ..lm.model import make_tensor_name

        try:
            make_tensor_name(0, self.layer_loc)
        except (ValueError, TypeError, KeyError, IndexError):
            # TypeError: non-string (YAML ints); Key/IndexError: template
            # placeholders other than {layer}
            raise ValueError(f"unknown layer_loc {self.layer_loc!r}")
        if self.batch_size <= 0 or self.n_chunks <= 0:
            raise ValueError("batch_size and n_chunks must be positive")


@dataclass
class EnsembleArgs(TrainArgs):
    """(reference `EnsembleArgs`, `config.py:54-58`)"""

    activation_width: int = 512
    use_synthetic_dataset: bool = False
    bias_decay: float = 0.0
    # topk sweeps: approx_max_k recall_target. None → exact TopKEncoder in
    # `topk_experiment`; set (e.g. 0.95) → TopKEncoderApprox at that recall
    topk_recall: Optional[float] = None


@dataclass
class SyntheticEnsembleArgs(EnsembleArgs):
    """(reference `SyntheticEnsembleArgs`, `config.py:60-69`)"""

    noise_magnitude_scale: float = 0.0
    feature_prob_decay: float = 0.99
    feature_num_nonzero: int = 10
    gen_batch_size: int = 4096
    dataset_folder: str = "activation_data"
    n_ground_truth_components: int = 512
    correlated_components: bool = False


@dataclass
class ErasureArgs(BaseArgs):
    """(reference `ErasureArgs`, `config.py:71-79`)"""

    model_name: str = "EleutherAI/pythia-70m-deduped"
    layer: Optional[int] = None
    count_cutoff: int = 10000
    output_folder: str = "output_erasure_pca"
    activation_filename: str = "activation_data_erasure.npz"
    dict_filename: str = ""


@dataclass
class ToyArgs(BaseArgs):
    """(reference `ToyArgs`, `config.py:81-110`)"""

    layer: int = 2
    layer_loc: str = "residual"
    model_name: str = "EleutherAI/pythia-70m-deduped"
    dataset_name: str = "openwebtext"
    tied_ae: bool = False
    seed: int = 0
    learned_dict_ratio: float = 1.0
    output_folder: str = "outputs"
    dtype: str = "float32"
    activation_dim: int = 256
    feature_prob_decay: float = 0.99
    feature_num_nonzero: int = 5
    correlated_components: bool = False
    n_ground_truth_components: int = 512
    noise_std: float = 0.1
    l1_exp_low: int = -12
    l1_exp_high: int = -11
    l1_exp_base: float = 10 ** (1 / 4)
    dict_ratio_exp_low: int = 1
    dict_ratio_exp_high: int = 7
    dict_ratio_exp_base: float = 2.0
    batch_size: int = 4096
    lr: float = 1e-3
    epochs: int = 1
    noise_level: float = 0.0
    n_components_dictionary: int = 512
    l1_alpha: float = 1e-3


@dataclass
class InterpArgs(BaseArgs):
    """(reference `InterpArgs`, `config.py:112-126`)"""

    layer: int = 2
    model_name: str = "EleutherAI/pythia-70m-deduped"
    layer_loc: str = "residual"
    n_feats_explain: int = 10
    load_interpret_autoencoder: str = ""
    tied_ae: bool = False
    interp_name: str = ""
    sort_mode: str = "max"
    use_decoder: bool = True
    df_n_feats: int = 200
    top_k: int = 50
    save_loc: str = ""
    # context inputs (no network needed when all three are set):
    # lm_params: pickle of (params, LMConfig) from lm.convert; fragments:
    # .npy [n, fragment_len] int tokens; token_strs: json list mapping token
    # id -> string. Empty ⇒ resolved from model_name/dataset via HF cache.
    lm_params: str = ""
    fragments: str = ""
    token_strs: str = ""
    dataset_name: str = "openwebtext"
    results_base: str = "auto_interp_results"  # reference BASE_FOLDER
    # >1: thread-pool fan-out of per-feature explain/simulate API calls
    # (the reference's async MAX_CONCURRENT, `interpret.py:59,337,354`)
    max_concurrent: int = 1

    def validate(self):
        if self.sort_mode not in ("max", "mean"):
            raise ValueError(f"sort_mode must be max|mean, got {self.sort_mode}")


@dataclass
class InterpGraphArgs(BaseArgs):
    """(reference `InterpGraphArgs`, `config.py:129-135`)"""

    layer: int = 1
    model_name: str = "EleutherAI/pythia-70m-deduped"
    layer_loc: str = "mlp"
    score_mode: str = "all"
    run_all: bool = False
    results_base: str = "auto_interp_results"

    def validate(self):
        if self.score_mode not in ("top", "random", "top_random", "all"):
            raise ValueError(f"bad score_mode {self.score_mode}")


@dataclass
class InvestigateArgs(BaseArgs):
    """(reference `InvestigateArgs`, `config.py:137-140`)"""

    threshold: float = 0.9
    layer: int = 2
