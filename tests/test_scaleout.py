"""HLO collective-traffic accounting used by scripts/scaleout_model.py.

The projection artifact's load-bearing numbers come from parsing collective
ops out of optimized SPMD HLO; these tests pin the parser on representative
HLO lines (shapes, tuple outputs, replica-group forms) and the ring-model
wire math. The full script (compiles 5 sharded programs on a 16-device
virtual mesh) runs as the SCALEOUT artifact, not in the suite.
"""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent / "scripts"))

from scaleout_model import _group_size, _shape_bytes, collective_traffic


def test_shape_bytes():
    assert _shape_bytes("f32[8,512,4096]{2,1,0}") == 8 * 512 * 4096 * 4
    assert _shape_bytes("bf16[2048,1024]") == 2048 * 1024 * 2
    # tuple outputs sum their elements
    assert _shape_bytes("(f32[8], f32[8,16])") == 8 * 4 + 8 * 16 * 4
    assert _shape_bytes("pred[]") == 1  # 0-d scalar: one element


def test_group_size_forms():
    assert _group_size("all-reduce(...), replica_groups={{0,1},{2,3}}", 16) == 2
    assert _group_size("all-reduce(...), replica_groups=[4,4]<=[16]", 16) == 4
    assert _group_size("all-reduce(...)", 16) == 16  # default: all devices


def test_collective_traffic_ring_models():
    hlo = """
HloModule jit_step
%ar = f32[2,4096,512]{2,1,0} all-reduce(f32[2,4096,512] %g), replica_groups={{0,1}}, to_apply=%add
%ag = f32[16,1024]{1,0} all-gather(f32[1,1024] %x), replica_groups=[1,16]<=[16], dimensions={0}
%cp = bf16[128]{0} collective-permute(bf16[128] %y), source_target_pairs={{0,1}}
"""
    t = collective_traffic(hlo, 16)
    by_op = {o["op"]: o for o in t["ops"]}
    ar_bytes = 2 * 4096 * 512 * 4
    # all-reduce over group 2: 2*(g-1)/g*b == b
    assert by_op["all-reduce"]["wire_bytes_per_chip"] == ar_bytes
    # all-gather: (g-1)/g of the gathered output
    ag_bytes = 16 * 1024 * 4
    assert by_op["all-gather"]["wire_bytes_per_chip"] == round(15 / 16 * ag_bytes)
    # permute: one hop
    assert by_op["collective-permute"]["wire_bytes_per_chip"] == 128 * 2
    assert t["wire_bytes_per_chip_per_step"] == sum(
        o["wire_bytes_per_chip"] for o in t["ops"]
    )


def test_async_collectives_counted_once():
    """TPU HLO emits async -start/-done pairs; traffic must count once."""
    hlo = """
%s0 = f32[1024]{0} all-reduce-start(f32[1024] %g), replica_groups={{0,1}}, to_apply=%add
%d0 = f32[1024]{0} all-reduce-done(f32[1024] %s0)
"""
    t = collective_traffic(hlo, 2)
    assert len(t["ops"]) == 1
    assert t["ops"][0]["op"] == "all-reduce"
    assert t["wire_bytes_per_chip_per_step"] == 1024 * 4  # 2*(1/2)*b


def test_non_collective_lines_ignored():
    hlo = "%d = f32[4096,512] dot(f32[4096,2048] %a, f32[2048,512] %b)"
    t = collective_traffic(hlo, 8)
    assert t["ops"] == [] and t["wire_bytes_per_chip_per_step"] == 0
