"""Smoke tests for the one-off analysis experiments (reference experiments/),
on tiny synthetic fixtures: each produces its figure/CSV and sane numbers."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sparse_coding__tpu.lm import LMConfig, init_params
from sparse_coding__tpu.models.learned_dict import TiedSAE


@pytest.fixture(scope="module")
def tiny_lm():
    cfg = LMConfig(
        arch="neox", n_layers=2, d_model=16, n_heads=2, d_mlp=32,
        vocab_size=64, n_ctx=32,
    )
    params = init_params(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 12), 0, 64)
    return cfg, params, tokens


def _random_tied(n, d, key):
    return TiedSAE(jax.random.normal(key, (n, d)), jnp.zeros((n,)), norm_encoder=True)


def test_pca_perplexity(tiny_lm, tmp_path):
    from sparse_coding__tpu.experiments import run_pca_perplexity

    cfg, params, tokens = tiny_lm
    acts = jax.random.normal(jax.random.PRNGKey(2), (512, cfg.d_model))
    dict_sets = {"Linear": [(_random_tied(24, cfg.d_model, jax.random.PRNGKey(3)), {"dict_size": 24})]}
    scores = run_pca_perplexity(
        params, cfg, (1, "residual"), tokens, acts, dict_sets, tmp_path,
        n_sample=256, noise_mags=[0.0, 0.3], pca_step=4, token_batch=4,
    )
    assert set(scores) == {"Linear", "Added Noise", "PCA (dynamic)", "PCA (static)"}
    for pts in scores.values():
        assert all(np.isfinite(v) for fvu, loss in pts for v in (fvu, loss))
    # zero added noise == identity: FVU ~ 0
    assert scores["Added Noise"][0][0] < 1e-5
    # more PCA components => lower FVU (monotone non-increasing-ish)
    fvus = [f for f, _ in scores["PCA (static)"]]
    assert fvus[0] > fvus[-1]
    assert (tmp_path / "pca_perplexity.png").exists()
    assert (tmp_path / "pca_perplexity.csv").exists()


def test_embedding_cosine_check(tiny_lm, tmp_path):
    from sparse_coding__tpu.experiments import run_embedding_cosine_check

    cfg, params, _ = tiny_lm
    # a dict made OF embedding rows must score ~1 on the embed panel
    embed_dict = TiedSAE(params["embed"][:10], jnp.zeros((10,)), norm_encoder=True)
    rand_dict = _random_tied(10, cfg.d_model, jax.random.PRNGKey(4))
    data = run_embedding_cosine_check(
        params, {0: [("1", embed_dict)], 1: [("1", rand_dict)]}, tmp_path
    )
    assert data[0][0][1] > 0.999  # embed panel, embedding-copy dict
    assert data[1][0][1] < 0.9
    assert (tmp_path / "embed_unembed.png").exists()


def test_moment_corrs(tmp_path):
    from sparse_coding__tpu.experiments import run_moment_corrs

    d, n = 16, 12
    ld = _random_tied(n, d, jax.random.PRNGKey(5))
    chunk = jax.random.normal(jax.random.PRNGKey(6), (512, d))
    # fake an autointerp results folder in the on-disk format
    results = tmp_path / "results"
    for f in range(6):
        folder = results / f"feature_{f:04d}"
        folder.mkdir(parents=True)
        (folder / "explanation.txt").write_text(
            f"something\nScore: {0.1 * f:.2f}\nTop only score: {0.2 * f:.2f}\n"
            f"Random only score: {0.05 * f:.2f}\n"
        )
    out = run_moment_corrs([(ld, chunk, results)], tmp_path / "out", batch_size=128)
    assert set(out["pooled"]) == {"n_active", "mean", "var", "skew", "kurtosis", "l4_norm"}
    assert (tmp_path / "out" / "moment_corrs.csv").exists()
    assert len(out["per_entry"]) == 1


def test_read_transform_scores_modes(tmp_path):
    from sparse_coding__tpu.interp.pipeline import read_transform_scores

    folder = tmp_path / "feature_0003"
    folder.mkdir()
    (folder / "explanation.txt").write_text(
        "expl\nScore: 0.50\nTop only score: 0.80\nRandom only score: 0.20\n"
    )
    ndxs, scores = read_transform_scores(tmp_path, score_mode="top")
    assert ndxs == [3] and scores == [0.8]
    _, scores = read_transform_scores(tmp_path, score_mode="random")
    assert scores == [0.2]


def test_investigate(tmp_path):
    from sparse_coding__tpu.experiments import random_feature_diversity, run_investigate

    d = 32
    larger = _random_tied(64, d, jax.random.PRNGKey(7))
    # smaller dict: half copied from larger (converged), half random
    rows = jnp.concatenate(
        [larger.get_learned_dict()[:8], jax.random.normal(jax.random.PRNGKey(8), (8, d))]
    )
    smaller = TiedSAE(rows, jnp.zeros((16,)), norm_encoder=True)
    summary = run_investigate(smaller, larger, tmp_path, threshold=0.9)
    assert summary["n_above_threshold"] >= 8
    assert np.isfinite(summary["enn_mmcs_correlation"])
    assert (tmp_path / "enn_vs_mmcs.png").exists()

    mean_enn = random_feature_diversity(tmp_path, n=500, d=d)
    # random unit vectors in R^d have ENN well below d but far above 1
    assert 2 < mean_enn < d


def test_l1_warmup_reaches_builders_and_warns_for_topk():
    """EnsembleArgs.l1_warmup_steps flows through the experiment builders to
    every l1-family Ensemble; a TopK builder warns and drops it instead of
    raising (one sweep may mix families) — VERDICT r4 next #2 + ADVICE."""
    import warnings

    from sparse_coding__tpu.train.experiments import (
        dense_l1_range_experiment,
        topk_experiment,
    )
    from sparse_coding__tpu.utils.config import EnsembleArgs

    cfg = EnsembleArgs(activation_width=16, l1_warmup_steps=7, batch_size=32)
    (ens_l1, _, _), = dense_l1_range_experiment(cfg)[0]
    assert ens_l1.l1_warmup_steps == 7

    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        ens_topk, _, _ = topk_experiment(cfg)[0][0]  # first of 4 ratio stacks
    assert ens_topk.l1_warmup_steps == 0
    assert any("l1_warmup" in str(x.message) for x in w), [str(x.message) for x in w]
