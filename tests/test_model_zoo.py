"""Every trainable DictSignature trains under the stacked-ensemble runtime.

One parameterized contract test: init two members with different hyperparams,
run the fused vmapped step, assert finite decreasing loss, and round-trip the
`to_learned_dict` export (encode/decode shapes, unit-norm dictionary rows).
This is coverage the reference lacks entirely (SURVEY.md §4).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sparse_coding__tpu.ensemble import build_ensemble
from sparse_coding__tpu import models as M

D_ACT, N_DICT, BATCH = 24, 48, 64

# (signature, common_hparams, per-member hparams list, train steps)
ZOO = [
    (M.FunctionalSAE, dict(activation_size=D_ACT, n_dict_components=N_DICT),
     [{"l1_alpha": 1e-4}, {"l1_alpha": 1e-3}], 30),
    (M.FunctionalTiedSAE, dict(activation_size=D_ACT, n_dict_components=N_DICT),
     [{"l1_alpha": 1e-4}, {"l1_alpha": 1e-3}], 30),
    (M.FunctionalTiedCenteredSAE, dict(activation_size=D_ACT, n_dict_components=N_DICT),
     [{"l1_alpha": 1e-4}, {"l1_alpha": 1e-3}], 30),
    (M.FunctionalThresholdingSAE, dict(activation_size=D_ACT, n_dict_components=N_DICT),
     [{"l1_alpha": 1e-4}, {"l1_alpha": 1e-3}], 30),
    (M.FunctionalMaskedTiedSAE,
     dict(activation_size=D_ACT, n_components_stack=N_DICT),
     [{"l1_alpha": 1e-4, "n_dict_components": 16},
      {"l1_alpha": 1e-3, "n_dict_components": 48}], 30),
    (M.FunctionalMaskedSAE,
     dict(activation_size=D_ACT, n_components_stack=N_DICT),
     [{"l1_alpha": 1e-4, "n_dict_components": 16},
      {"l1_alpha": 1e-3, "n_dict_components": 48}], 30),
    (M.FunctionalReverseSAE, dict(activation_size=D_ACT, n_dict_components=N_DICT),
     [{"l1_alpha": 1e-4}, {"l1_alpha": 1e-3}], 30),
    (M.TopKEncoder, dict(d_activation=D_ACT, n_features=N_DICT, sparsity_cap=12),
     [{"sparsity": 4}, {"sparsity": 12}], 30),
    (M.FunctionalFista, dict(activation_size=D_ACT, n_dict_components=N_DICT),
     [{"l1_alpha": 1e-4}, {"l1_alpha": 1e-3}], 30),
    (M.FunctionalLISTADenoisingSAE,
     dict(d_activation=D_ACT, n_features=N_DICT, n_hidden_layers=3),
     [{"l1_alpha": 1e-4}, {"l1_alpha": 1e-3}], 40),
    (M.FunctionalResidualDenoisingSAE,
     dict(d_activation=D_ACT, n_features=N_DICT, n_hidden_layers=3),
     [{"l1_alpha": 1e-4}, {"l1_alpha": 1e-3}], 40),
    (M.FunctionalPositiveTiedSAE, dict(activation_size=D_ACT, n_dict_components=N_DICT),
     [{"l1_alpha": 1e-4}, {"l1_alpha": 1e-3}], 30),
    (M.SemiLinearSAE, dict(activation_size=D_ACT, n_dict_components=N_DICT),
     [{"l1_alpha": 1e-4}, {"l1_alpha": 1e-3}], 30),
    (M.DirectCoefOptimizer, dict(d_activation=D_ACT, n_features=N_DICT),
     [{"l1_alpha": 1e-3}, {"l1_alpha": 1e-2}], 10),
]


@pytest.fixture(scope="module")
def batch():
    key = jax.random.PRNGKey(7)
    k_d, k_c, k_m = jax.random.split(key, 3)
    D = jax.random.normal(k_d, (N_DICT, D_ACT))
    D = D / jnp.linalg.norm(D, axis=-1, keepdims=True)
    codes = jax.random.uniform(k_c, (BATCH, N_DICT)) * jax.random.bernoulli(
        k_m, 0.15, (BATCH, N_DICT)
    )
    return codes @ D


@pytest.mark.parametrize("sig,common,members,steps", ZOO, ids=lambda z: getattr(z, "__name__", None))
def test_signature_trains_and_exports(sig, common, members, steps, batch):
    ens = build_ensemble(
        sig,
        jax.random.PRNGKey(0),
        members,
        optimizer_kwargs={"learning_rate": 3e-3},
        **common,
    )
    losses = []
    for _ in range(steps):
        loss_dict, aux = ens.step_batch(batch)
        losses.append(jax.device_get(loss_dict["loss"]))
    first, last = losses[0], losses[-1]
    assert np.isfinite(last).all(), f"{sig.__name__}: non-finite loss {last}"
    assert (last <= first + 1e-6).all(), f"{sig.__name__}: loss went up {first}->{last}"
    # aux code has [n_models, batch, n_feats(-stack)] shape
    assert aux["c"].shape[0] == len(members)
    assert aux["c"].shape[1] == BATCH

    for ld in ens.to_learned_dicts():
        d = ld.get_learned_dict()
        assert d.shape[1] == D_ACT
        c = ld.encode(batch)
        assert c.shape == (BATCH, d.shape[0])
        x_hat = ld.predict(batch)
        assert x_hat.shape == batch.shape
        assert np.isfinite(np.asarray(x_hat)).all()
        norms = np.asarray(jnp.linalg.norm(d, axis=-1))
        # rows are unit-norm (or zero for never-used padded rows)
        assert ((np.abs(norms - 1.0) < 1e-4) | (norms < 1e-6)).all()
