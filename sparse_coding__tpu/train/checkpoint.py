"""Checkpointing: full training-state save/resume + learned-dict exports.

The reference only ever saves *outputs* — `(LearnedDict, hyperparams)` lists at
exponential chunk counts (`big_sweep.py:421-427`) — and has no way to resume
training (SURVEY.md §5 "checkpoint/resume: save-only"). Here:

  - `save_ensemble_checkpoint` / `restore_ensemble_checkpoint`: orbax
    checkpoints of every ensemble's FULL state (params + buffers + optimizer
    state + step) plus the sweep cursor (chunk index, RNG seed), giving true
    resume — the TPU failure-recovery story (multi-host preemption = restart
    from checkpoint).
  - `save_learned_dicts` / `load_learned_dicts`: the reference's on-disk
    export format, re-expressed as a pickle of pytree-flattened LearnedDicts
    with numpy leaves (portable, no framework pinning). All analysis tooling
    consumes this format, exactly as everything in the reference consumes
    `learned_dicts.pt`.
"""

from __future__ import annotations

import pickle
from pathlib import Path
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np


# -- learned-dict export (the reference's learned_dicts.pt) -------------------

def save_learned_dicts(path, learned_dicts: List[Tuple[Any, Dict[str, Any]]]):
    """Save a `[(LearnedDict, hyperparams), ...]` list.

    Records store fields BY NAME (`{class, arrays, statics}`) via the
    LearnedDict registry — never pickled treedefs, whose leaf order silently
    shifts (corrupting loads) if a class's pytree registration changes between
    save and load. Non-registered values (e.g. nested pytrees inside a field)
    are handled by `jax.tree.map` over the field value.
    """
    from sparse_coding__tpu.models.learned_dict import LEARNED_DICT_REGISTRY

    records = []
    for ld, hyperparams in learned_dicts:
        if type(ld) not in LEARNED_DICT_REGISTRY:
            raise TypeError(
                f"{type(ld).__name__} is not a registered LearnedDict; register "
                "it with register_learned_dict before saving"
            )
        array_fields, static_fields = LEARNED_DICT_REGISTRY[type(ld)]
        records.append(
            {
                "class": f"{type(ld).__module__}.{type(ld).__qualname__}",
                "arrays": {
                    f: jax.tree.map(
                        lambda l: np.asarray(jax.device_get(l)), getattr(ld, f)
                    )
                    for f in array_fields
                },
                "statics": {f: getattr(ld, f, None) for f in static_fields},
                "hyperparams": hyperparams,
            }
        )
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    with open(path, "wb") as f:
        pickle.dump(records, f)


def load_learned_dicts(path) -> List[Tuple[Any, Dict[str, Any]]]:
    import importlib

    with open(path, "rb") as f:
        records = pickle.load(f)
    out = []
    for rec in records:
        if "treedef" in rec:
            # the round-1 treedef-pickle format: unflattening an old treedef
            # with a class whose registration has since changed SILENTLY
            # mis-assigns fields (e.g. AddedNoise's noise_mag static→leaf
            # move), so refuse loudly rather than corrupt
            raise ValueError(
                f"{path} uses the removed treedef-pickle learned-dict format; "
                "re-export it with save_learned_dicts (field-name records)"
            )
        else:
            mod_name, _, cls_name = rec["class"].rpartition(".")
            cls = getattr(importlib.import_module(mod_name), cls_name)
            ld = cls.__new__(cls)
            for f, v in rec["arrays"].items():
                setattr(ld, f, jax.tree.map(jax.numpy.asarray, v))
            for f, v in rec["statics"].items():
                setattr(ld, f, v)
        out.append((ld, rec["hyperparams"]))
    return out


# -- full training-state checkpoints (orbax) ----------------------------------

def _checkpointer():
    import orbax.checkpoint as ocp

    return ocp.PyTreeCheckpointer()


def save_ensemble_checkpoint(
    ckpt_dir,
    ensembles: List[Tuple[Any, Dict[str, Any], str]],
    chunk_cursor: int = 0,
    extra: Optional[Dict[str, Any]] = None,
):
    """Save full sweep state: every ensemble's metadata + LIVE state + cursor.

    `ensembles` is the sweep's `[(Ensemble, args, name), ...]` list. The
    state is saved from the live (possibly mesh-sharded) device arrays —
    orbax writes each process's addressable shards locally, so pod-scale
    states are never gathered to one host (`jax.device_get` on a multi-host
    global array would raise on non-addressable shards, and even
    single-host it would needlessly round-trip the whole state through host
    RAM). Pairs with the sharded restore in `restore_ensemble_checkpoint`.
    """
    ckpt_dir = Path(ckpt_dir).absolute()
    tree = {
        "cursor": {"chunk": chunk_cursor, **(extra or {})},
        "ensembles": {
            name: ens.state_template() for ens, _args, name in ensembles
        },
        "args": {name: _args for _ens, _args, name in ensembles},
    }
    _checkpointer().save(ckpt_dir, tree, force=True)


def restore_ensemble_checkpoint(ckpt_dir, template: Optional[Dict[str, Any]] = None):
    """Restore the sweep tree saved by `save_ensemble_checkpoint`, or None if
    no checkpoint exists. Caller rebuilds ensembles via `Ensemble.from_state`.

    `template` is a same-structure pytree (e.g. built from freshly-initialized
    ensembles) used to recover exact leaf *types* — without it orbax returns
    plain dicts/lists, losing the `EnsembleState` dataclass and optax's
    NamedTuple optimizer states that the compiled step expects.

    Sharded restore: when template leaves are mesh-sharded `jax.Array`s
    (build the template with `Ensemble.state_template()` on sharded
    ensembles), orbax places each shard directly on its device — the restore
    never materializes the full state on one device, so ensembles that only
    fit HBM when distributed can actually resume.
    """
    ckpt_dir = Path(ckpt_dir).absolute()
    if not ckpt_dir.exists():
        return None
    ckpt = _checkpointer()
    if template is not None:
        import orbax.checkpoint as ocp

        if any(
            isinstance(leaf, jax.Array) for leaf in jax.tree.leaves(template)
        ):
            restore_args = ocp.checkpoint_utils.construct_restore_args(template)
            return ckpt.restore(ckpt_dir, item=template, restore_args=restore_args)
        return ckpt.restore(ckpt_dir, item=template)
    return ckpt.restore(ckpt_dir)


def latest_checkpoint(output_folder) -> Optional[Path]:
    """Most recent `ckpt_*` dir under the sweep output folder."""
    root = Path(output_folder)
    if not root.exists():
        return None
    ckpts = sorted(root.glob("ckpt_*"), key=lambda p: int(p.name.split("_")[1]))
    return ckpts[-1] if ckpts else None
