"""Device-mesh conventions and sharding inference for stacked ensembles.

This module replaces the reference's entire multi-device machinery — the
process-per-ensemble-per-GPU dispatch with host shared memory
(`cluster_runs.py:100-157`), the device-list popping placement
(`big_sweep_experiments.py:49-66`), and the gloo DDP experiment
(`experiments/huge_batch_size.py:259-345`) — with a single-controller JAX mesh
(SURVEY.md §2.4 P1-P6):

  axis "model" — ensemble/task parallelism (P1+P2): stacked ensemble members
                 are sharded across devices; no processes, no shared memory.
  axis "data"  — data parallelism (P3): the activation batch is sharded;
                 XLA inserts the gradient psum over ICI (the DDP allreduce).
                 Because SAE training data is a flattened (batch×seq)
                 activation stream, this axis IS the sequence-parallel axis —
                 there is no separate ring/Ulysses dimension to shard
                 (SURVEY.md §5 "long-context: absent by construction").
  axis "dict"  — tensor parallelism (P5): `n_dict_components` of each member
                 is sharded for ≥32× overcomplete dictionaries; the decode
                 einsum contracts over it, XLA inserts the psum.

Multi-host: the same mesh spans hosts via `jax.distributed.initialize` (see
`parallel/distributed.py`); ICI carries in-slice collectives, DCN cross-slice.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

MODEL_AXIS = "model"
DATA_AXIS = "data"
DICT_AXIS = "dict"


def make_mesh(
    model: int = 1,
    data: int = 1,
    dict_: int = 1,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a `(model, data, dict)` mesh over the given (default: all) devices.

    Axis sizes must multiply to the device count. Axes of size 1 are kept in
    the mesh (harmless) so downstream PartitionSpecs are uniform.
    """
    devices = list(devices if devices is not None else jax.devices())
    n = model * data * dict_
    if n != len(devices):
        raise ValueError(
            f"mesh {model}x{data}x{dict_} needs {n} devices, have {len(devices)}"
        )
    dev_array = np.asarray(devices).reshape(model, data, dict_)
    return Mesh(dev_array, (MODEL_AXIS, DATA_AXIS, DICT_AXIS))


def default_mesh_shape(n_devices: int, n_models: int = 1, want_dict: bool = False):
    """Heuristic (model, data, dict) factorization of `n_devices`.

    Greedy: give the model axis the largest divisor of `n_devices` that
    divides `n_models` (ensemble members are embarrassingly parallel — the
    cheapest axis); optionally carve a dict axis of 2; the rest is data.
    """
    model = 1
    for cand in range(min(n_models, n_devices), 0, -1):
        if n_devices % cand == 0 and n_models % cand == 0:
            model = cand
            break
    rest = n_devices // model
    dict_ = 2 if (want_dict and rest % 2 == 0) else 1
    data = rest // dict_
    return model, data, dict_


def batch_sharding(mesh: Mesh, leading: int = 0) -> NamedSharding:
    """Sharding for a `[batch, d_activation]` batch shared by all members:
    batch dim over the data axis, features replicated. ``leading`` prepends
    that many replicated axes (e.g. the scan-step axis of `step_scan`)."""
    return NamedSharding(mesh, P(*([None] * leading), DATA_AXIS, None))


def per_model_batch_sharding(mesh: Mesh, leading: int = 0) -> NamedSharding:
    """Sharding for a `[n_models, batch, d_activation]` per-member batch
    (``leading`` extra replicated axes prepended, e.g. scan steps)."""
    return NamedSharding(mesh, P(*([None] * leading), MODEL_AXIS, DATA_AXIS, None))


def infer_state_specs(state, n_models: int, mesh: Mesh, shard_dict: bool = True):
    """PartitionSpec pytree for an `EnsembleState`.

    Rules (per leaf):
      - leading dim == n_models → that dim goes on the model axis;
      - for rank-2/3 leaves with the model axis assigned, the next dim goes on
        the dict axis when divisible by its size (this captures encoder /
        decoder / bias / optimizer moments, whose dim 1 is n_dict_components;
        it also shards e.g. whitening matrices on their first non-model dim,
        which is a valid, memory-saving layout). Rank≥4 leaves are replicated
        past the model axis: their dim 1 is a structural axis (e.g. the
        scanned layer stack of LISTA's `encoder_layers`,
        `[n_models, K, n_feats, d]`), and sharding it would split every scan
        step's weights across devices;
      - everything else replicated.

    Optimizer state leaves (adam mu/nu) mirror the param shapes, so the same
    shape rule shards them identically — keeping update math local.
    """
    dict_size = mesh.shape[DICT_AXIS] if shard_dict else 1
    model_size = mesh.shape[MODEL_AXIS]
    if n_models % model_size != 0:
        raise ValueError(
            f"n_models={n_models} must be divisible by the mesh model axis "
            f"({model_size}); pad the ensemble or resize the mesh"
        )

    def leaf_spec(leaf):
        shape = getattr(leaf, "shape", ())
        if len(shape) == 0 or shape[0] != n_models:
            return P()
        axes = [MODEL_AXIS]
        if 2 <= len(shape) <= 3 and dict_size > 1 and shape[1] % dict_size == 0:
            axes.append(DICT_AXIS)
        axes += [None] * (len(shape) - len(axes))
        return P(*axes)

    return jax.tree.map(leaf_spec, state)


def shard_state(state, mesh: Mesh, n_models: int, shard_dict: bool = True):
    """`device_put` an EnsembleState onto the mesh per `infer_state_specs`."""
    specs = infer_state_specs(state, n_models, mesh, shard_dict)
    return jax.tree.map(
        lambda leaf, spec: jax.device_put(leaf, NamedSharding(mesh, spec)), state, specs
    )
