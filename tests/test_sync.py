"""Remote sync layer: command construction, scheme dispatch, retries —
all through an injected runner (no network). Reference `utils.py:30-222` /
`cmdutil.py` behaviors, minus the hardcoded hosts and key IDs."""

import subprocess
from types import SimpleNamespace

import pytest

from sparse_coding__tpu.utils import sync as S


class Recorder:
    def __init__(self, fail_times=0, stdout=""):
        self.calls = []
        self.fail_times = fail_times
        self.stdout = stdout

    def __call__(self, cmd):
        self.calls.append(cmd)
        rc = 1 if len(self.calls) <= self.fail_times else 0
        return SimpleNamespace(returncode=rc, stdout=self.stdout, stderr="boom")


def test_local_rsync_command():
    r = Recorder()
    S.sync("/a/", "/b", runner=r)
    assert r.calls[0][:3] == ["rsync", "-az", "--partial"]
    assert r.calls[0][-2:] == ["/a/", "/b"]
    assert "-e" not in r.calls[0]  # local: no ssh transport


def test_is_remote_deterministic(tmp_path, monkeypatch):
    """Remote detection never probes the filesystem (ADVICE r3): the same
    string classifies identically whatever exists in cwd, and rsync's own
    `./` prefix disambiguates colon-containing local names."""
    assert S._is_remote("host:proj")
    assert S._is_remote("user@host:proj")
    assert S._is_remote("gs://bucket/x") and S._is_remote("ssh://pod1/d")
    assert not S._is_remote("./weird:name")
    assert not S._is_remote("/abs/weird:name")
    # existence of a directory named like the host must not flip the answer
    monkeypatch.chdir(tmp_path)
    (tmp_path / "host").mkdir()
    assert S._is_remote("host:proj")


def test_ssh_rsync_with_port_and_excludes():
    r = Recorder()
    S.sync("/a/", "host:proj", excludes=["*.hdf", ".git"], ssh_port=2222, runner=r)
    cmd = r.calls[0]
    assert ["-e", "ssh -p 2222"] == cmd[-4:-2]
    assert cmd.count("--exclude") == 2


def test_include_list_semantics():
    # reference datasets_sync: include *.csv, exclude everything else —
    # with '*/' kept included so rsync still descends into subdirectories
    r = Recorder()
    S.sync("/a/", "host:proj", includes=["*.csv"], runner=r)
    cmd = r.calls[0]
    i = cmd.index("--include")
    assert cmd[i + 1] == "*/" and cmd[i + 2 : i + 4] == ["--include", "*.csv"]
    assert ["--exclude", "*", "--prune-empty-dirs"] == cmd[i + 4 : i + 7]


def test_ssh_url_scheme_converted():
    r = Recorder()
    S.sync("ssh://pod1/data/", "/local", runner=r)
    assert r.calls[0][-2:] == ["pod1:data/", "/local"]


def test_gcs_and_s3_dispatch():
    r = Recorder()
    S.sync("/a/", "gs://bucket/x", delete=True, excludes=["*.hdf", ".git"], runner=r)
    cmd = r.calls[0]
    assert cmd[:5] == ["gsutil", "-m", "rsync", "-r", "-d"]
    # ONE -x carrying a joined regex (gsutil keeps only the last -x flag)
    assert cmd.count("-x") == 1
    import fnmatch, re
    rx = cmd[cmd.index("-x") + 1]
    assert re.fullmatch(rx, "a.hdf") and re.fullmatch(rx, ".git")
    assert not re.fullmatch(rx, "keep.npy")
    S.sync("s3://bucket/x", "/a", excludes=["*.pkl"], runner=r)
    assert r.calls[1][:4] == ["aws", "s3", "sync", "s3://bucket/x"]
    assert "--exclude" in r.calls[1]
    # s3 include-list: exclude-everything must precede the re-includes
    S.sync("/a/", "s3://bucket/x", includes=["*.csv"], runner=r)
    cmd = r.calls[2]
    assert cmd.index("--exclude") < cmd.index("--include")
    assert cmd[cmd.index("--exclude") + 1] == "*"
    with pytest.raises(ValueError):
        S.sync("gs://a/x", "s3://b/y", runner=r)


def test_retry_then_success_and_failure():
    r = Recorder(fail_times=2)
    S.sync("/a/", "/b", retries=3, runner=r)
    assert len(r.calls) == 3
    r2 = Recorder(fail_times=5)
    with pytest.raises(RuntimeError, match="boom"):
        S.sync("/a/", "/b", retries=2, runner=r2)


def test_backoff_schedule_and_env_config(monkeypatch):
    """The shared retry engine (ISSUE 5 satellite): SC_SYNC_RETRIES /
    SC_SYNC_BACKOFF configure attempts + base delay, and the slept schedule
    is exponential with an 8 s cap."""
    monkeypatch.setenv(S.RETRIES_ENV, "5")
    monkeypatch.setenv(S.BACKOFF_ENV, "0.5")
    assert S.default_retries() == 5 and S.default_backoff() == 0.5
    assert S.backoff_delays(5, 0.5) == [0.5, 1.0, 2.0, 4.0]
    assert S.backoff_delays(7, 2.0) == [2.0, 4.0, 8.0, 8.0, 8.0, 8.0]

    slept, attempts = [], []

    def fn(attempt):
        attempts.append(attempt)
        raise OSError("transient")

    with pytest.raises(OSError):
        S.retry_with_backoff(fn, sleep=slept.append)
    assert attempts == [0, 1, 2, 3, 4], "env-configured attempt count"
    assert slept == [0.5, 1.0, 2.0, 4.0], "env-configured backoff schedule"

    # sync() rides the same engine: 5 env-default attempts, same sleeps
    slept.clear()
    monkeypatch.setattr(S.time, "sleep", slept.append)
    r = Recorder(fail_times=99)
    with pytest.raises(RuntimeError, match="after 5 attempts"):
        S.sync("/a/", "/b", runner=r)
    assert len(r.calls) == 5 and slept == [0.5, 1.0, 2.0, 4.0]

    # garbage env values fall back to the defaults rather than crashing
    monkeypatch.setenv(S.RETRIES_ENV, "many")
    monkeypatch.setenv(S.BACKOFF_ENV, "soon")
    assert S.default_retries() == 3 and S.default_backoff() == 1.0


def test_retry_with_backoff_on_retry_hook():
    seen = []

    def fn(attempt):
        if attempt < 2:
            raise OSError("flaky")
        return "ok"

    out = S.retry_with_backoff(
        fn, attempts=4, base_delay=0.0,
        on_retry=lambda attempt, exc: seen.append((attempt, str(exc))),
    )
    assert out == "ok"
    assert seen == [(0, "flaky"), (1, "flaky")]


def test_task_wrappers_use_env_remote(monkeypatch, tmp_path):
    monkeypatch.setenv("SC_TPU_REMOTE", "gs://bucket/proj/")
    r = Recorder()
    S.push_outputs(tmp_path / "outputs", runner=r)
    assert r.calls[0][-1] == "gs://bucket/proj/outputs/"
    S.push_dataset(tmp_path / "acts", runner=r)
    assert r.calls[1][-1] == "gs://bucket/proj/datasets/"
    monkeypatch.delenv("SC_TPU_REMOTE")
    with pytest.raises(ValueError, match="SC_TPU_REMOTE"):
        S.push_outputs(tmp_path)


def test_pull_latest_outputs(tmp_path):
    r = Recorder(stdout="proj/outputs/run_42/\n")
    S.pull_latest_outputs(remote="host:proj", local=tmp_path, runner=r)
    # first call lists, second syncs the newest run folder
    assert r.calls[0][0] == "ssh" and "ls -td" in r.calls[0][-1]
    assert r.calls[1][-2] == "host:proj/outputs/run_42/"
    assert str(tmp_path / "run_42") == r.calls[1][-1]
    with pytest.raises(ValueError):
        S.pull_latest_outputs(remote="gs://bucket/x", local=tmp_path, runner=r)


def test_local_python_fallback(tmp_path, monkeypatch):
    """Minimal images without rsync: local syncs work through the pure-python
    mirror (same include semantics, nested dirs included)."""
    src = tmp_path / "src"
    (src / "sub").mkdir(parents=True)
    (src / "a.csv").write_text("1")
    (src / "sub" / "b.csv").write_text("2")
    (src / "c.txt").write_text("3")

    def no_tool(cmd):
        raise FileNotFoundError(cmd[0])

    S.sync(f"{src}/", str(tmp_path / "dst"), includes=["*.csv"], runner=no_tool)
    assert (tmp_path / "dst" / "a.csv").exists()
    assert (tmp_path / "dst" / "sub" / "b.csv").exists()
    assert not (tmp_path / "dst" / "c.txt").exists()
    # remote targets still demand the real tool
    with pytest.raises(RuntimeError, match="not installed"):
        S.sync(f"{src}/", "host:proj", runner=no_tool)
