"""Filesystem-backed work queue: atomic claims, leases, dead-lease reaping.

The fleet layer (docs/FLEET.md) shards a sweep into member-group *work
items* and drives them across many preemptible workers. This module is the
coordination substrate — plain files on a shared filesystem (the one place
a TPU fleet always agrees on), no database, no extra daemon:

    <fleet_dir>/queue/
        pending/<item>.json    items awaiting a claim
        leased/<item>.json     claimed items (the SAME file, moved)
        leases/<item>.json     who holds it + when the lease expires
        done/<item>.json       verified-complete items
        failed/<item>.json     items whose attempt budget is exhausted
        workers/<worker>.json  per-worker ledger (strikes, quarantine) —
                               written ONLY by the scheduler
        seen/<worker>.json     per-worker liveness stamp — written ONLY by
                               the worker itself

The ledger/liveness split is a single-writer-per-file rule: strikes and
quarantine flags are scheduler-owned, last-seen stamps are worker-owned, so
no unsynchronized read-modify-write can ever erase a quarantine (a worker
re-writing a stale copy of its own ledger while the scheduler strikes it).
Per-worker completion counts are derived from item lineage, not stored.

Correctness rests on two filesystem guarantees and nothing else:

  - **Atomic claim.** A worker claims an item by `os.replace`-ing its file
    from `pending/` into `leased/` — rename is atomic, so exactly one of N
    racing workers wins; the losers see `FileNotFoundError` and move on.
  - **At-least-once, exactly-committed.** A claimed item may be executed
    more than once (a worker can die after training but before
    completion), but it is *committed* exactly once: `complete()` verifies
    lease ownership and `os.replace`s the item into `done/` — the single
    commit point, mirroring the checkpoint protocol in
    `train.checkpoint.save_checkpoint_tree`.

Liveness comes from **leases**: a claim writes a lease file with an expiry;
the worker's heartbeat thread renews it (rewrite via temp + `os.replace`)
while the item trains. A worker that dies stops renewing; the scheduler's
`reap_expired()` moves the item back to `pending/` with its `attempt`
bumped and a lineage entry recording which worker lost it — the
reassignment trail `fleet.report` renders. Renewal is read-verify-write,
so a zombie worker whose lease was reaped gets `LeaseLost` instead of
silently resurrecting it.

Workers that keep losing leases (bad host, sick HBM, flaky NFS mount) are
**quarantined** after `quarantine_after` strikes: their ledger file gains
`quarantined: true` and their own `claim()` calls return nothing — graceful
degradation, not a reassignment stampede onto the same broken machine.

Every item carries its own history: `attempt` (0-based claim count) and
`lineage` (one entry per claim: worker, timestamps, outcome, the
checkpoint it resumed from). The history travels WITH the item file
through every move, so the fleet report needs no join against event logs
to reconstruct who lost what and where it resumed.
"""

from __future__ import annotations

import json
import os
import time
from pathlib import Path
from typing import Any, Callable, Dict, List, Optional

__all__ = [
    "LeaseLost",
    "WorkQueue",
    "is_fleet_dir",
]

_BUCKETS = ("pending", "leased", "done", "failed")


class LeaseLost(RuntimeError):
    """The caller no longer holds the lease it is acting under (expired and
    reaped, or the item was reassigned/completed by someone else)."""


def _write_json(path: Path, obj: Dict[str, Any]) -> None:
    """Atomic JSON write: same-dir temp + `os.replace` (the idiom every
    commit point in this repo uses — a kill mid-write leaves the previous
    complete file or nothing, never a torn one)."""
    path.parent.mkdir(parents=True, exist_ok=True)
    tmp = path.with_name(f".{path.name}.tmp{os.getpid()}")
    with open(tmp, "w") as f:
        json.dump(obj, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def _read_json(path: Path) -> Optional[Dict[str, Any]]:
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return None


def is_fleet_dir(path) -> bool:
    """Does `path` hold a fleet queue? (`queue/pending/` is created by the
    first `WorkQueue` construction and never removed.)"""
    return (Path(path) / "queue" / "pending").is_dir()


def _check_id(name: str, what: str) -> str:
    if not name or any(c in name for c in "/\\\0") or name.startswith("."):
        raise ValueError(f"invalid {what} id {name!r} (must be a plain file name)")
    return name


class WorkQueue:
    """One fleet's work queue rooted at `<fleet_dir>/queue/`.

    Many processes may hold a `WorkQueue` on the same directory — all
    cross-process coordination is the rename protocol above; the object
    itself keeps no authoritative state.
    """

    def __init__(self, fleet_dir, create: bool = True):
        self.fleet_dir = Path(fleet_dir)
        self.root = self.fleet_dir / "queue"
        if create:
            for b in _BUCKETS + ("leases", "workers", "seen"):
                (self.root / b).mkdir(parents=True, exist_ok=True)
        elif not self.root.is_dir():
            raise FileNotFoundError(f"no fleet queue under {self.fleet_dir}")

    # -- paths ----------------------------------------------------------------

    def _item_path(self, bucket: str, item_id: str) -> Path:
        return self.root / bucket / f"{item_id}.json"

    def _lease_path(self, item_id: str) -> Path:
        return self.root / "leases" / f"{item_id}.json"

    def _worker_path(self, worker_id: str) -> Path:
        return self.root / "workers" / f"{worker_id}.json"

    def _seen_path(self, worker_id: str) -> Path:
        return self.root / "seen" / f"{worker_id}.json"

    def run_dir(self, item_id: str) -> Path:
        """The item's training output directory (`<fleet_dir>/runs/<item>`)
        — checkpoints, learned-dict exports, and events land here, and a
        reassigned item resumes from whatever committed checkpoint the
        previous holder left."""
        return self.fleet_dir / "runs" / _check_id(item_id, "item")

    # -- submission -----------------------------------------------------------

    def submit(
        self,
        item_id: str,
        members: List[str],
        payload: Dict[str, Any],
    ) -> Dict[str, Any]:
        """Enqueue one work item. `members` names the ensemble members the
        item trains (the unit the zero-lost-members guarantee is counted
        in); `payload` tells the worker how to run it (see
        `fleet.worker.run_item`)."""
        _check_id(item_id, "item")
        for bucket in _BUCKETS:
            if self._item_path(bucket, item_id).exists():
                raise FileExistsError(f"item {item_id!r} already exists in {bucket}/")
        item = {
            "item": item_id,
            "members": list(members),
            "payload": dict(payload),
            "attempt": 0,
            "submitted_ts": time.time(),
            "lineage": [],
        }
        _write_json(self._item_path("pending", item_id), item)
        return item

    # -- worker ledger / quarantine -------------------------------------------

    def worker_record(self, worker_id: str) -> Dict[str, Any]:
        """Scheduler-owned ledger (strikes/quarantine) merged with the
        worker-owned liveness stamp. Read-only composition — neither writer
        ever rewrites the other's file."""
        rec = _read_json(self._worker_path(worker_id)) or {
            "worker": worker_id, "strikes": 0, "quarantined": False,
        }
        seen = _read_json(self._seen_path(worker_id))
        if seen and seen.get("last_seen_ts") is not None:
            rec["last_seen_ts"] = float(seen["last_seen_ts"])
        return rec

    def worker_quarantined(self, worker_id: str) -> bool:
        return bool(self.worker_record(worker_id).get("quarantined"))

    def touch_seen(self, worker_id: str) -> None:
        """Worker-side liveness stamp. Deliberately NOT the ledger file:
        the ledger is scheduler-owned, so a concurrent strike/quarantine
        can never be erased by a worker's stale read-modify-write."""
        _write_json(
            self._seen_path(worker_id),
            {"worker": worker_id, "last_seen_ts": time.time()},
        )

    def strike_worker(
        self, worker_id: str, reason: str, quarantine_after: Optional[int] = None
    ) -> Dict[str, Any]:
        """One strike against a worker (an expired or failed lease). After
        `quarantine_after` strikes the worker is quarantined: its own
        `claim()` calls return None, so reassignment flows to healthy
        workers instead of stampeding back onto a repeat offender. Called
        ONLY by the scheduler — the ledger's single writer."""
        rec = _read_json(self._worker_path(worker_id)) or {
            "worker": worker_id, "strikes": 0, "quarantined": False,
        }
        rec["strikes"] = int(rec.get("strikes", 0)) + 1
        rec.setdefault("strike_reasons", []).append(reason)
        if quarantine_after is not None and rec["strikes"] >= quarantine_after:
            rec["quarantined"] = True
        _write_json(self._worker_path(worker_id), rec)
        return self.worker_record(worker_id)

    # -- claim / renew --------------------------------------------------------

    def claim(
        self, worker_id: str, lease_seconds: float = 30.0
    ) -> Optional[Dict[str, Any]]:
        """Claim the first available pending item, or None (empty queue or
        quarantined worker). The rename IS the mutual exclusion; the lease
        file written right after it is the liveness contract."""
        _check_id(worker_id, "worker")
        if self.worker_quarantined(worker_id):
            return None
        self.touch_seen(worker_id)
        now = time.time()
        for src in sorted((self.root / "pending").glob("*.json")):
            if src.name.startswith("."):
                continue  # a writer's temp file
            dst = self.root / "leased" / src.name
            try:
                os.replace(src, dst)  # atomic: exactly one claimer wins
            except FileNotFoundError:
                continue  # lost the race for this item; try the next
            try:
                # rename preserves mtime; stamp the CLAIM time so the
                # reaper's claim-without-lease grace window measures from
                # here, not from however long the item sat in pending/
                os.utime(dst)
            except OSError:
                pass
            item = _read_json(dst)
            if item is None:  # torn submit (should be impossible; be safe)
                continue
            item["lineage"].append(
                {
                    "attempt": int(item.get("attempt", 0)),
                    "worker": worker_id,
                    "claimed_ts": now,
                    "outcome": "running",
                }
            )
            _write_json(dst, item)
            _write_json(
                self._lease_path(item["item"]),
                {
                    "item": item["item"],
                    "worker": worker_id,
                    "claimed_ts": now,
                    "renewed_ts": now,
                    "expires_ts": now + float(lease_seconds),
                    "renewals": 0,
                },
            )
            return item
        return None

    def _owned_lease(self, item_id: str, worker_id: str) -> Dict[str, Any]:
        lease = _read_json(self._lease_path(item_id))
        if lease is None or lease.get("worker") != worker_id:
            raise LeaseLost(
                f"worker {worker_id} no longer holds the lease on {item_id} "
                f"(held by {lease.get('worker') if lease else 'nobody'})"
            )
        return lease

    def renew(
        self, item_id: str, worker_id: str, lease_seconds: float = 30.0
    ) -> Dict[str, Any]:
        """Heartbeat: extend the lease. Read-verify-write, so a reaped lease
        raises `LeaseLost` instead of being silently resurrected by a
        zombie holder."""
        lease = self._owned_lease(item_id, worker_id)
        now = time.time()
        lease.update(
            renewed_ts=now,
            expires_ts=now + float(lease_seconds),
            renewals=int(lease.get("renewals", 0)) + 1,
        )
        _write_json(self._lease_path(item_id), lease)
        return lease

    def note(self, item_id: str, worker_id: str, **fields) -> None:
        """Record fields (e.g. ``resumed_from``) on the current lineage
        entry of a leased item — the reassignment trail the fleet report
        renders."""
        self._owned_lease(item_id, worker_id)
        path = self._item_path("leased", item_id)
        item = _read_json(path)
        if item is None or not item.get("lineage"):
            raise LeaseLost(f"leased item {item_id} vanished")
        item["lineage"][-1].update(fields)
        _write_json(path, item)

    # -- completion / failure -------------------------------------------------

    def complete(
        self, item_id: str, worker_id: str, result: Optional[Dict[str, Any]] = None
    ) -> Dict[str, Any]:
        """Commit the item as done. Requires a live owned lease; the
        `os.replace` into `done/` is the exactly-once commit point."""
        self._owned_lease(item_id, worker_id)
        src = self._item_path("leased", item_id)
        item = _read_json(src)
        if item is None:
            raise LeaseLost(f"leased item {item_id} vanished")
        item["lineage"][-1].update(outcome="done", completed_ts=time.time())
        if result:
            item["result"] = result
            # the export manifest's content digest (ISSUE 19): recorded in
            # the lineage entry itself so the provenance graph joins this
            # item to its export by digest even if `result` is later
            # rewritten by a requeue_done round trip
            if result.get("export_digest"):
                item["lineage"][-1]["export_digest"] = result["export_digest"]
        _write_json(src, item)
        os.replace(src, self._item_path("done", item_id))
        self._lease_path(item_id).unlink(missing_ok=True)
        self.touch_seen(worker_id)
        return item

    def _requeue(
        self,
        item: Dict[str, Any],
        src: Path,
        outcome: str,
        max_attempts: Optional[int],
        **fields,
    ) -> str:
        """Move a leased item back to pending (attempt+1) or, past the
        attempt budget, to failed/. Returns the destination bucket."""
        item["lineage"][-1].update(outcome=outcome, released_ts=time.time(), **fields)
        item["attempt"] = int(item.get("attempt", 0)) + 1
        lost = max_attempts is not None and item["attempt"] >= max_attempts
        bucket = "failed" if lost else "pending"
        _write_json(src, item)
        os.replace(src, self._item_path(bucket, item["item"]))
        self._lease_path(item["item"]).unlink(missing_ok=True)
        return bucket

    def fail(
        self,
        item_id: str,
        worker_id: str,
        error: str,
        max_attempts: Optional[int] = None,
        outcome: str = "failed",
    ) -> str:
        """Graceful failure: the worker saw the item's run die and releases
        it for another attempt. Returns the bucket the item landed in
        ('pending' or, budget exhausted, 'failed'). ``outcome`` names the
        lineage entry's terminal mark — e.g. ``input_corrupt`` when the
        admission check found the item's chunk store rotten (mirroring the
        scheduler's post-completion ``export_corrupt`` requeues)."""
        self._owned_lease(item_id, worker_id)
        src = self._item_path("leased", item_id)
        item = _read_json(src)
        if item is None:
            raise LeaseLost(f"leased item {item_id} vanished")
        return self._requeue(item, src, outcome, max_attempts, error=str(error)[:500])

    def release(self, item_id: str, worker_id: str, outcome: str = "released") -> None:
        """Voluntary release WITHOUT an attempt penalty (worker shutting
        down / preempted after committing a resumable checkpoint)."""
        self._owned_lease(item_id, worker_id)
        src = self._item_path("leased", item_id)
        item = _read_json(src)
        if item is None:
            raise LeaseLost(f"leased item {item_id} vanished")
        item["lineage"][-1].update(outcome=outcome, released_ts=time.time())
        _write_json(src, item)
        os.replace(src, self._item_path("pending", item_id))
        self._lease_path(item_id).unlink(missing_ok=True)

    def requeue_done(
        self,
        item_id: str,
        outcome: str,
        error: str,
        max_attempts: Optional[int] = None,
    ) -> Optional[tuple]:
        """Send a done/ item back for retraining (post-completion export
        corruption) through the SAME lineage/attempt/budget protocol as
        every other requeue. Returns (bucket, item) — 'pending' or, budget
        exhausted, 'failed' — or None if the item is no longer in done/."""
        src = self._item_path("done", item_id)
        item = _read_json(src)
        if item is None:
            return None
        item.setdefault("lineage", []).append(
            {"attempt": int(item.get("attempt", 0)), "worker": None}
        )
        bucket = self._requeue(item, src, outcome, max_attempts, error=str(error)[:500])
        return bucket, item

    # -- reaping (scheduler side) ---------------------------------------------

    def reap_expired(
        self,
        now: Optional[float] = None,
        max_attempts: Optional[int] = None,
        quarantine_after: Optional[int] = None,
        grace_seconds: float = 30.0,
        on_event: Optional[Callable[[str, Dict[str, Any]], None]] = None,
    ) -> List[Dict[str, Any]]:
        """Reassign dead work. For every leased item whose lease has
        expired (worker stopped heartbeating — killed, hung, partitioned):
        strike the worker, delete the lease, and requeue the item with its
        lineage recording who lost it. Leased items with NO lease file
        (claimer died between the claim rename and the lease write) are
        requeued after `grace_seconds` of no modification. Returns one
        action record per reassignment; `on_event(kind, fields)` mirrors
        them to telemetry."""
        now = time.time() if now is None else now
        actions: List[Dict[str, Any]] = []

        def emit(kind: str, **fields):
            actions.append({"kind": kind, **fields})
            if on_event is not None:
                on_event(kind, fields)

        # lease files whose item is no longer leased (a completer died
        # between the done-commit rename and the lease unlink) are inert —
        # sweep them so they can't shadow a future claim of the same id
        for stale in sorted((self.root / "leases").glob("*.json")):
            if stale.name.startswith("."):
                continue
            if not self._item_path("leased", stale.stem).exists():
                stale.unlink(missing_ok=True)

        for path in sorted((self.root / "leased").glob("*.json")):
            if path.name.startswith("."):
                continue
            item_id = path.stem
            lease = _read_json(self._lease_path(item_id))
            if lease is not None and float(lease.get("expires_ts", 0)) > now:
                continue  # live lease
            if lease is None:
                # claim rename landed but the lease write never did — only a
                # worker death in that tiny window produces this state
                try:
                    if now - path.stat().st_mtime < grace_seconds:
                        continue
                except OSError:
                    continue
            item = _read_json(path)
            if item is None:
                continue
            if lease is not None:
                worker = lease.get("worker")
            elif item.get("lineage") and item["lineage"][-1].get("outcome") == "running":
                # the claimer died after appending its lineage entry but
                # before the lease write — the entry names it
                worker = item["lineage"][-1].get("worker")
            else:
                # died between the claim rename and the lineage write: the
                # claimer is unknowable — never strike the PREVIOUS
                # attempt's holder for a lease it didn't claim
                worker = None
            if worker:
                rec = self.strike_worker(
                    worker, f"lease_expired:{item_id}", quarantine_after
                )
                if rec.get("quarantined") and rec["strikes"] == quarantine_after:
                    emit("quarantine", worker=worker, strikes=rec["strikes"])
            if not item.get("lineage"):
                item["lineage"].append(
                    {"attempt": int(item.get("attempt", 0)), "worker": worker,
                     "outcome": "running"}
                )
            age = now - float((lease or {}).get("renewed_ts", 0) or 0)
            bucket = self._requeue(
                item, path, "lease_expired", max_attempts,
                lease_age_seconds=round(age, 3) if lease is not None else None,
            )
            emit(
                "lease_expired",
                item=item_id,
                worker=worker,
                attempt=item["attempt"],
                requeued_to=bucket,
            )
            if bucket == "failed":
                emit(
                    "item_lost",
                    item=item_id,
                    members=item.get("members", []),
                    attempts=item["attempt"],
                )
        return actions

    # -- inspection (monitor / report side) ------------------------------------

    def items(self, bucket: str) -> List[Dict[str, Any]]:
        out = []
        for p in sorted((self.root / bucket).glob("*.json")):
            if p.name.startswith("."):
                continue
            item = _read_json(p)
            if item is not None:
                out.append(item)
        return out

    def leases(self) -> List[Dict[str, Any]]:
        out = []
        for p in sorted((self.root / "leases").glob("*.json")):
            if p.name.startswith("."):
                continue
            lease = _read_json(p)
            if lease is not None:
                out.append(lease)
        return out

    def workers(self) -> List[Dict[str, Any]]:
        """Every worker the fleet has heard of: ledger entries (struck or
        quarantined) plus seen-only workers that have claimed cleanly."""
        ids = set()
        for sub in ("workers", "seen"):
            for p in (self.root / sub).glob("*.json"):
                if not p.name.startswith("."):
                    ids.add(p.stem)
        return [self.worker_record(w) for w in sorted(ids)]

    def finished(self) -> bool:
        """No work outstanding: every item is in done/ or failed/."""
        for bucket in ("pending", "leased"):
            for p in (self.root / bucket).glob("*.json"):
                if not p.name.startswith("."):
                    return False
        return True

    def state(self, now: Optional[float] = None) -> Dict[str, Any]:
        """One coherent snapshot for the monitor's fleet view and the fleet
        report: item/member counts per state, per-worker liveness, lease
        ages. Members of leased items split into *running* (live lease) vs
        *orphaned* (expired/missing lease, awaiting reassignment); members
        of failed items are *lost* — the number chaos tests pin to zero."""
        now = time.time() if now is None else now
        leases = {l["item"]: l for l in self.leases()}
        state: Dict[str, Any] = {
            "now": now,
            "items": {b: self.items(b) for b in _BUCKETS},
            "leases": leases,
            "workers": self.workers(),
        }
        members = {"queued": 0, "running": 0, "orphaned": 0, "done": 0, "lost": 0}
        for item in state["items"]["pending"]:
            members["queued"] += len(item.get("members", []))
        for item in state["items"]["done"]:
            members["done"] += len(item.get("members", []))
        for item in state["items"]["failed"]:
            members["lost"] += len(item.get("members", []))
        for item in state["items"]["leased"]:
            lease = leases.get(item["item"])
            live = lease is not None and float(lease.get("expires_ts", 0)) > now
            members["running" if live else "orphaned"] += len(item.get("members", []))
        state["members"] = members
        state["item_counts"] = {b: len(state["items"][b]) for b in _BUCKETS}
        # per-worker completion counts, derived from lineage rather than
        # stored in the ledger (which is scheduler-owned — see touch_seen)
        done_by_worker: Dict[str, int] = {}
        for bucket in _BUCKETS:
            for item in state["items"][bucket]:
                for entry in item.get("lineage", []):
                    if entry.get("outcome") == "done" and entry.get("worker"):
                        w = entry["worker"]
                        done_by_worker[w] = done_by_worker.get(w, 0) + 1
        state["done_by_worker"] = done_by_worker
        return state
