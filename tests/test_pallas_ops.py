"""Pallas kernels: numerics vs the pure-jnp reference path (interpret mode on
CPU; the same kernel compiles natively on TPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sparse_coding__tpu.models.fista import fista
from sparse_coding__tpu.ops import fista_pallas

pytestmark = pytest.mark.kernels


@pytest.fixture(scope="module")
def planted():
    key = jax.random.PRNGKey(0)
    k_d, k_c, k_m = jax.random.split(key, 3)
    n, d, b = 32, 16, 96  # b deliberately not a multiple of the batch tile
    D = jax.random.normal(k_d, (n, d))
    D = D / jnp.linalg.norm(D, axis=-1, keepdims=True)
    codes = jax.random.uniform(k_c, (b, n)) * jax.random.bernoulli(k_m, 0.1, (b, n))
    return D, codes @ D


@pytest.mark.parametrize("l1", [1e-4, 1e-2])
def test_pallas_matches_reference(planted, l1):
    D, x = planted
    a_ref, res_ref = fista(x, D, jnp.asarray(l1), jnp.zeros((x.shape[0], D.shape[0])), num_iter=100)
    a_pl, res_pl = fista_pallas(x, D, l1, num_iter=100, batch_tile=64, interpret=True)
    np.testing.assert_allclose(np.asarray(a_pl), np.asarray(a_ref), atol=1e-4)
    np.testing.assert_allclose(np.asarray(res_pl), np.asarray(res_ref), atol=1e-4)


def test_pallas_solves(planted):
    D, x = planted
    a, res = fista_pallas(x, D, 1e-4, num_iter=300, batch_tile=32, interpret=True)
    assert float(jnp.mean(res**2)) < 1e-4 * float(jnp.mean(x**2))
    assert float(a.min()) >= 0.0


def test_pallas_warm_start(planted):
    D, x = planted
    import jax.numpy as jnp
    warm, _ = fista_pallas(x, D, 1e-3, num_iter=200, batch_tile=32, interpret=True)
    a_w, res_w = fista_pallas(x, D, 1e-3, num_iter=10, coefficients=warm,
                              batch_tile=32, interpret=True)
    a_c, res_c = fista_pallas(x, D, 1e-3, num_iter=10, batch_tile=32, interpret=True)
    assert float(jnp.mean(res_w**2)) <= float(jnp.mean(res_c**2)) + 1e-8


def test_fista_decoder_update_pallas_path(planted):
    """Train-loop decoder update with the pallas solver (interpret on CPU)
    must produce the same result as the jnp path."""
    import jax
    import jax.numpy as jnp
    from sparse_coding__tpu.ensemble import build_ensemble
    from sparse_coding__tpu.models import FunctionalFista
    from sparse_coding__tpu.train import make_fista_decoder_update

    D, x = planted
    def fresh():
        return build_ensemble(
            FunctionalFista, jax.random.PRNGKey(5),
            [{"l1_alpha": 1e-3}, {"l1_alpha": 1e-4}],
            optimizer_kwargs={"learning_rate": 1e-3},
            activation_size=x.shape[1], n_dict_components=D.shape[0],
        )
    ens1, ens2 = fresh(), fresh()
    c = jnp.zeros((2, x.shape[0], D.shape[0]))
    upd_jnp = make_fista_decoder_update(num_iter=50, use_pallas=False)
    upd_pl = make_fista_decoder_update(num_iter=50, use_pallas=True)
    s1 = upd_jnp(ens1.state, x, c)
    s2 = upd_pl(ens2.state, x, c)
    np.testing.assert_allclose(
        np.asarray(s1.params["decoder"]), np.asarray(s2.params["decoder"]), atol=1e-4
    )


def test_pallas_fits_heuristic():
    from sparse_coding__tpu.ops.fista_pallas import pallas_fits

    # small dictionaries fit the VMEM-resident kernel
    assert pallas_fits(256, 512, 128)
    # the bench shape measured-OOMs at the default tile — must not fit
    assert not pallas_fits(2048, 4096, 512)


def test_fista_solve_matches_fista():
    """The auto selector's XLA branch (and the None-coefficients default)
    must match the plain solver exactly. Uses a shape pallas_fits REJECTS so
    the XLA fallback is the branch under test on every backend."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from sparse_coding__tpu.models.fista import fista
    from sparse_coding__tpu.ops.fista_pallas import fista_solve, pallas_fits

    B, N, D = 256, 2048, 512
    assert not pallas_fits(B, N, D)  # guarantees the XLA branch below
    d = jax.random.normal(jax.random.PRNGKey(0), (N, D))
    d = d / jnp.linalg.norm(d, axis=1, keepdims=True)
    x = jax.random.normal(jax.random.PRNGKey(1), (B, D))
    a1, r1 = fista_solve(x, d, 1e-3, None, num_iter=20)
    a2, r2 = fista(x, d, 1e-3, jnp.zeros((B, N)), 20)
    np.testing.assert_allclose(np.asarray(a1), np.asarray(a2), atol=1e-6)
    np.testing.assert_allclose(np.asarray(r1), np.asarray(r2), atol=1e-6)


def test_hbm_dict_kernel_matches_fista(planted):
    """v2 kernel (single-VMEM-scratch dictionary, VERDICT r2 next #10):
    numerics pinned to `models.fista.fista` in interpret mode, including
    padding (batch not a multiple of the tile) and warm starts."""
    from sparse_coding__tpu.ops.fista_pallas import fista_pallas_hbm_dict

    d, x = planted
    ref, ref_res = fista(x, d, 1e-3, jnp.zeros((x.shape[0], d.shape[0])), 60)
    got, got_res = fista_pallas_hbm_dict(
        x, d, 1e-3, num_iter=60, batch_tile=8, interpret=True
    )
    np.testing.assert_allclose(np.asarray(ref), np.asarray(got), atol=1e-5)
    np.testing.assert_allclose(np.asarray(ref_res), np.asarray(got_res), atol=1e-5)
    # warm start
    warm = ref * 0.5
    ref2, _ = fista(x, d, 1e-3, warm, 30)
    got2, _ = fista_pallas_hbm_dict(
        x, d, 1e-3, num_iter=30, coefficients=warm, batch_tile=8, interpret=True
    )
    np.testing.assert_allclose(np.asarray(ref2), np.asarray(got2), atol=1e-5)
