"""Structured run-event log: every training run explains itself from artifacts.

`RunTelemetry` writes an append-only `events.jsonl` next to the metrics JSONL
(`utils.logging.MetricLogger`). One record per line:

    {"seq": <monotonic int>, "ts": <unix float>, "event": <kind>, ...fields}

Kinds (see docs/observability.md for the full schema):
  - ``run_start``   config + environment fingerprint (git SHA, jax/backend
                    versions, device/mesh topology, compile-cache state)
  - ``compile``     one jit compilation: entry-point name + wall seconds
                    (attributed by `tracked_jit`; aggregate backend counts
                    additionally arrive via the `jax.monitoring` bridge)
  - ``chunk_start`` / ``chunk_end``   per training chunk, with wall seconds
  - ``phase``       a named timed section (`utils.trace.timed`)
  - ``anomaly``     emitted by `telemetry.anomaly.AnomalyGuard` (or any caller)
  - ``snapshot``    one flush of ALL monotonic counters + gauges
  - ``run_end``     exit status, step totals, steps/sec

Counters and gauges are host-side Python scalars — incrementing them never
touches the device, so telemetry preserves the repo's no-per-step-host-sync
invariant (SURVEY.md §7). They reach disk only via `snapshot()` (and the
automatic one inside `run_end`).

The `jax.monitoring` bridge (`_install_jax_listeners`) subscribes ONCE per
process and fans out to every live RunTelemetry: backend compile durations
(`/jax/core/compile/backend_compile_duration`) and persistent-compile-cache
events (`/jax/compilation_cache/*` — the `utils.compile_cache` hit/miss
signal) become counters. `tracked_jit` adds per-entry-point attribution the
global events cannot provide: it watches a jitted callable's executable cache
grow and emits a named ``compile`` event with the call's wall time.
"""

from __future__ import annotations

import json
import subprocess
import sys
import threading
import time
from pathlib import Path
from typing import Any, Callable, Dict, List, Optional

__all__ = [
    "DEFAULT_LATENCY_BUCKETS_MS",
    "RunTelemetry",
    "counter_add_float_active",
    "counter_inc_active",
    "event_active",
    "gauge_set_active",
    "run_fingerprint",
    "tracked_jit",
    "read_events",
]

# fixed log-spaced latency buckets (ms): 0.25 ms … 2048 ms, each bound 2x
# the previous — the /metrics histogram contract (docs/observability.md §8).
# FIXED, not adaptive: histograms from different writers/generations must
# merge by plain bucket addition, and a quantile read off the buckets is
# then correct to within one bucket width by construction.
DEFAULT_LATENCY_BUCKETS_MS = tuple(0.25 * 2 ** i for i in range(14))


# Live instances receiving process-global signals (jax.monitoring, tracked_jit
# compile detections). Appended on construction, removed on close().
_ACTIVE: List["RunTelemetry"] = []
_LISTENERS_LOCK = threading.Lock()
_LISTENERS_INSTALLED = False


def _install_jax_listeners() -> None:
    """Register the process-wide `jax.monitoring` bridge (idempotent)."""
    global _LISTENERS_INSTALLED
    with _LISTENERS_LOCK:
        if _LISTENERS_INSTALLED:
            return
        _LISTENERS_INSTALLED = True
    try:
        import jax.monitoring as mon

        def _suppressed() -> bool:
            # profiling.jit_cost_fields(memory=True) compiles a throwaway
            # executable for its memory_analysis — that compile must not
            # count as run compile activity (it would corrupt the
            # compile-state confound signal bench.py reports)
            try:
                from sparse_coding__tpu.telemetry.profiling import monitoring_suppressed

                return monitoring_suppressed()
            except Exception:  # pragma: no cover - import cycle during teardown
                return False

        def on_duration(event: str, duration: float, **kw):
            if event.endswith("backend_compile_duration") and not _suppressed():
                for t in list(_ACTIVE):
                    t.counter_inc("compile.backend.count")
                    t.counter_add_float("compile.backend.seconds", duration)

        def on_event(event: str, **kw):
            # '/jax/compilation_cache/cache_hits', '.../cache_misses',
            # '.../compile_requests_use_cache', ... — the persistent
            # compile-cache traffic enable_persistent_compile_cache turns on
            if event.startswith("/jax/compilation_cache/") and not _suppressed():
                for t in list(_ACTIVE):
                    t.counter_inc(f"compile_cache.{event.rsplit('/', 1)[-1]}")

        mon.register_event_duration_secs_listener(on_duration)
        mon.register_event_listener(on_event)
    except Exception:  # pragma: no cover - jax without monitoring
        pass


def counter_inc_active(name: str, n: int = 1) -> None:
    """Bump a counter on EVERY live RunTelemetry — the hook for layers that
    hold no telemetry handle (e.g. `data.chunks` transient-read retries
    feeding the `io.retry` counter). No live telemetry → no-op."""
    for t in list(_ACTIVE):
        t.counter_inc(name, n)


def counter_add_float_active(name: str, v: float) -> None:
    """Float-add a counter on EVERY live RunTelemetry — the fractional
    sibling of `counter_inc_active` (e.g. handle-less span seconds)."""
    for t in list(_ACTIVE):
        t.counter_add_float(name, v)


def gauge_set_active(name: str, value: float) -> None:
    """Set a gauge on EVERY live RunTelemetry — for handle-less layers whose
    state is a level, not a count (e.g. `data.integrity.ChunkLossBudget`'s
    remaining-budget fraction). No live telemetry → no-op."""
    for t in list(_ACTIVE):
        t.gauge_set(name, value)


def event_active(etype: str, **fields) -> None:
    """Emit an event on EVERY live RunTelemetry — the event-shaped sibling
    of `counter_inc_active`, for layers with no telemetry handle whose
    occurrences deserve a timeline entry (e.g. `train.checkpoint`'s
    checkpoint-fallback anomalies). No live telemetry → no-op."""
    for t in list(_ACTIVE):
        t.event(etype, **fields)


def run_fingerprint(mesh=None) -> Dict[str, Any]:
    """Environment fingerprint for `run_start`: enough to re-identify how a
    run was produced from its artifacts alone (the ISSUE-2 requirement), all
    best-effort — a fingerprint must never fail a training run. A field
    group that fails to resolve lands in ``fingerprint_error`` instead of
    silently vanishing (a fingerprint whose backend/process keys are simply
    absent is indistinguishable from an old-schema log; the error string is
    not). Excepts are narrow per group so one failure cannot drop the
    others."""
    fp: Dict[str, Any] = {"python": sys.version.split()[0]}
    errors: List[str] = []
    jax = None
    try:
        import jax
        import jaxlib

        fp["jax"] = jax.__version__
        fp["jaxlib"] = jaxlib.__version__
    except (ImportError, AttributeError) as e:
        errors.append(f"jax_version: {e!r}")
    if jax is not None:
        try:
            devs = jax.devices()
            fp["backend"] = devs[0].platform
            fp["device_kind"] = devs[0].device_kind
            fp["device_count"] = len(devs)
        except (RuntimeError, IndexError, AttributeError) as e:
            errors.append(f"devices: {e!r}")
        try:
            fp["process_index"] = int(jax.process_index())
            fp["process_count"] = int(jax.process_count())
        except (RuntimeError, AttributeError) as e:
            errors.append(f"process: {e!r}")
    try:
        from sparse_coding__tpu.telemetry.multihost import clock_state

        clock = clock_state()
        if clock:
            # pod runs: the coordinator clock offset that aligns this host's
            # timestamps with the merged timeline
            fp["clock_offset_seconds"] = clock.get("offset_seconds")
            fp["clock_uncertainty_seconds"] = clock.get("uncertainty_seconds")
    except Exception:  # pragma: no cover - import cycle during teardown
        pass
    if errors:
        fp["fingerprint_error"] = "; ".join(errors)
    try:
        repo = Path(__file__).resolve().parents[2]
        sha = subprocess.run(
            ["git", "rev-parse", "HEAD"], cwd=repo, capture_output=True,
            text=True, timeout=5,
        )
        if sha.returncode == 0:
            fp["git_sha"] = sha.stdout.strip()
    except Exception:
        pass
    try:
        from sparse_coding__tpu.utils.compile_cache import compile_cache_info

        fp["compile_cache"] = compile_cache_info()
    except Exception:
        pass
    if mesh is not None:
        try:
            fp["mesh"] = {str(k): int(v) for k, v in mesh.shape.items()}
        except Exception:
            fp["mesh"] = str(mesh)
    return fp


class RunTelemetry:
    """Append-only structured event log + monotonic counters/gauges.

    ``out_dir=None`` keeps everything in memory (counters still aggregate —
    the bench uses this to report compile stats without writing artifacts).
    The instance is also a context manager: ``__exit__`` writes ``run_end``
    (status "ok", or "error: <exc>" when exiting on an exception) unless one
    was already written, then closes the file.

    Multi-host runs (``jax.process_count() > 1``, see
    `telemetry.multihost` / docs/observability.md §5): the file becomes
    ``events.p<i>.jsonl`` and every record is tagged ``process_index`` so
    merged timelines and anomalies know their originating host. Single-host
    layout (``events.jsonl``, untagged) is a stability contract.
    """

    def __init__(
        self,
        out_dir: Optional[str] = None,
        run_name: str = "run",
        config: Optional[Dict[str, Any]] = None,
        file_name: str = "events.jsonl",
        install_jax_listeners: bool = True,
        tags: Optional[Dict[str, Any]] = None,
    ):
        self.run_name = run_name
        self._config = config
        # constant fields stamped into EVERY record (e.g. a serve replica's
        # ``{"replica": "replica0"}``) so merged run dirs can attribute
        # events/snapshots per writer — the serve replica tier's report and
        # monitor views key on this
        self.tags = dict(tags or {})
        self._lock = threading.Lock()
        self._seq = 0
        self._t0 = time.time()
        self._t0_mono = time.monotonic()
        self._chunk_t0_mono: Optional[float] = None
        self._counters: Dict[str, float] = {}
        self._gauges: Dict[str, float] = {}
        self._hists: Dict[str, Dict[str, Any]] = {}
        self._run_end_written = False
        self._fh = None
        self.path: Optional[Path] = None
        from sparse_coding__tpu.telemetry import multihost as _mh

        idx, count = _mh.process_info()
        self.process_index: Optional[int] = idx if count > 1 else None
        self.generation = 0
        if out_dir is not None:
            d = Path(out_dir)
            d.mkdir(parents=True, exist_ok=True)
            self.path = d / _mh.per_process_file_name(file_name, idx, count)
            # generation index: a resumed process APPENDS to the same log, so
            # the number of run_start records already on disk IS this
            # generation's index — the key that lets goodput/report sum wall
            # time across generations instead of under-reporting a resumed
            # run as only its last generation (ISSUE 9 satellite)
            self.generation = self._count_prior_generations()
            self._fh = open(self.path, "a")
        if install_jax_listeners:
            _install_jax_listeners()
        _ACTIVE.append(self)

    def _count_prior_generations(self) -> int:
        """run_start records already in this process's log file (0 on a fresh
        run). A substring scan, not a JSON parse: the writer below emits
        exactly ``"event": "run_start"`` and torn tail lines must not matter."""
        if self.path is None or not self.path.exists():
            return 0
        n = 0
        try:
            with open(self.path, "r", errors="replace") as f:
                for line in f:
                    if '"event": "run_start"' in line:
                        n += 1
        except OSError:
            return 0
        return n

    # -- raw event plumbing --------------------------------------------------

    def event(self, etype: str, **fields) -> Dict[str, Any]:
        """Write one event record of type `etype`; returns it (tests and
        callers may inspect). Field names are free — `anomaly` events carry
        their detector name under a ``kind`` field, for example. Every
        record carries both the wall clock (``ts`` — cross-host alignable
        via the clock-offset gauges) and a monotonic stamp (``mono`` —
        NTP-step-proof within a process generation)."""
        with self._lock:
            self._seq += 1
            rec = {
                "seq": self._seq, "ts": time.time(),
                "mono": round(time.monotonic(), 6), "event": etype,
                **self.tags, **fields,
            }
            if self.process_index is not None:
                rec["process_index"] = self.process_index
            if self._fh is not None:
                self._fh.write(json.dumps(rec, default=str) + "\n")
                self._fh.flush()
        return rec

    # -- lifecycle events ----------------------------------------------------

    def run_start(self, config: Optional[Dict[str, Any]] = None, mesh=None):
        """The first record: run name, caller config, environment
        fingerprint, and this process's resume generation index (0 = fresh;
        a supervised restart appending to the same log counts up)."""
        cfg = config if config is not None else self._config
        return self.event(
            "run_start",
            run_name=self.run_name,
            generation=self.generation,
            config=cfg,
            fingerprint=run_fingerprint(mesh=mesh),
        )

    def compile(
        self,
        name: str,
        seconds: float,
        cache_hit: Optional[bool] = None,
        cost: Optional[Dict[str, Any]] = None,
    ):
        """One jit compilation of entry point `name` (wall-clock seconds —
        trace + compile + the triggering dispatch). ``cost`` (optional) is a
        `telemetry.profiling.compiled_cost_fields` dict — analytic FLOPs /
        HBM bytes / memory footprints of the compiled executable; it rides
        the event under a ``cost`` key for the report's perf attribution."""
        self.counter_inc(f"compile.{name}.count")
        self.counter_add_float(f"compile.{name}.seconds", seconds)
        fields: Dict[str, Any] = {"name": name, "seconds": round(seconds, 4)}
        if cache_hit is not None:
            fields["cache_hit"] = bool(cache_hit)
        if cost:
            fields["cost"] = cost
        return self.event("compile", **fields)

    def chunk_start(self, chunk: int, **fields):
        self._chunk_t0 = time.time()
        self._chunk_t0_mono = time.monotonic()
        return self.event("chunk_start", chunk=int(chunk), **fields)

    def chunk_end(self, chunk: int, **fields):
        # monotonic-derived duration: an NTP clock step mid-chunk cannot
        # produce a negative/inflated window. No chunk_start → seconds=None
        # (rendered "n/a" downstream), never a fake 0 duration.
        t0 = self._chunk_t0_mono
        self._chunk_t0_mono = None
        self.counter_inc("chunks")  # the chunk completed either way
        if t0 is None:
            return self.event("chunk_end", chunk=int(chunk), seconds=None, **fields)
        dt = time.monotonic() - t0
        self.counter_add_float("chunk.seconds", dt)
        return self.event(
            "chunk_end", chunk=int(chunk), seconds=round(dt, 3), **fields
        )

    def anomaly(self, kind: str, **fields):
        self.counter_inc("anomalies")
        return self.event("anomaly", kind=kind, **fields)

    def run_end(self, status: str = "ok", timer_stats: Optional[Dict] = None, **fields):
        """Final record: exit status, step totals (from the counters), wall
        time, and optional `utils.trace.StepTimer.report()` stats. Emits a
        closing `snapshot` first so every counter survives in the log."""
        self.snapshot()
        self._run_end_written = True
        steps = self._counters.get("train.steps")
        # monotonic wall: THIS generation's span, clock-step-proof. Resumed
        # runs sum wall across generations in report/goodput (each run_end
        # carries its generation index) — a single generation's wall was
        # never the whole story for a killed-and-resumed run.
        wall = time.monotonic() - self._t0_mono
        rec: Dict[str, Any] = {
            "status": status,
            "run_name": self.run_name,
            "generation": self.generation,
            "wall_seconds": round(wall, 3),
            **fields,
        }
        if steps is not None:
            rec["steps"] = int(steps)
            rec.setdefault("steps_per_sec", round(steps / wall, 3) if wall > 0 else None)
        if timer_stats:
            rec["timer"] = {
                k: round(v, 4) if isinstance(v, float) else v
                for k, v in timer_stats.items()
            }
        return self.event("run_end", **rec)

    # -- counters / gauges ---------------------------------------------------

    def counter_inc(self, name: str, n: int = 1):
        """Monotonic counter bump — host-side only, no device sync."""
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + n

    def counter_add_float(self, name: str, v: float):
        with self._lock:
            self._counters[name] = self._counters.get(name, 0.0) + float(v)

    def gauge_set(self, name: str, value: float):
        with self._lock:
            self._gauges[name] = float(value)

    def hist_observe(self, name: str, value: float,
                     buckets: Optional[tuple] = None):
        """Record one observation into a fixed-bucket histogram (created on
        first observe; ``buckets`` only matters then). Host-side like the
        counters — no device sync. Flushed by `snapshot` and rendered by
        `telemetry.metrics_http` as a Prometheus histogram."""
        v = float(value)
        with self._lock:
            h = self._hists.get(name)
            if h is None:
                bounds = tuple(
                    float(b)
                    for b in (buckets or DEFAULT_LATENCY_BUCKETS_MS)
                )
                h = self._hists[name] = {
                    "bounds": bounds,
                    "counts": [0] * (len(bounds) + 1),  # +1 = overflow
                    "sum": 0.0,
                    "count": 0,
                }
            h["sum"] += v
            h["count"] += 1
            for i, b in enumerate(h["bounds"]):
                if v <= b:
                    h["counts"][i] += 1
                    break
            else:
                h["counts"][-1] += 1

    @property
    def counters(self) -> Dict[str, float]:
        return dict(self._counters)

    @property
    def gauges(self) -> Dict[str, float]:
        return dict(self._gauges)

    @property
    def hists(self) -> Dict[str, Dict[str, Any]]:
        with self._lock:
            return {
                k: {
                    "bounds": list(h["bounds"]),
                    "counts": list(h["counts"]),
                    "sum": h["sum"],
                    "count": h["count"],
                }
                for k, h in self._hists.items()
            }

    def snapshot(self):
        """ONE flush of every counter and gauge as a single event (plus the
        histograms, only when any exist — snapshot schema for runs without
        them is a byte-stability contract)."""
        with self._lock:
            counters = {
                k: round(v, 4) if isinstance(v, float) else v
                for k, v in sorted(self._counters.items())
            }
            gauges = {k: v for k, v in sorted(self._gauges.items())}
            hists = {
                k: {
                    "bounds": list(h["bounds"]),
                    "counts": list(h["counts"]),
                    "sum": round(h["sum"], 4),
                    "count": h["count"],
                }
                for k, h in sorted(self._hists.items())
            }
        if hists:
            return self.event(
                "snapshot", counters=counters, gauges=gauges, hists=hists
            )
        return self.event("snapshot", counters=counters, gauges=gauges)

    # -- lifetime ------------------------------------------------------------

    def close(self, status: str = "ok"):
        if self in _ACTIVE:
            _ACTIVE.remove(self)
        if not self._run_end_written:
            self.run_end(status=status)
        if self._fh is not None:
            self._fh.close()
            self._fh = None

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        self.close(status="ok" if exc_type is None else f"error: {exc_type.__name__}: {exc}")
        return False


def read_events(path) -> List[Dict[str, Any]]:
    """Parse an events.jsonl back into records (the schema round-trip)."""
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


class _TrackedJit:
    """Transparent wrapper around a jitted callable that attributes compiles.

    On each call (only while some RunTelemetry is live — otherwise a single
    list check and straight through): reads the function's executable-cache
    size before/after, and when it grew, publishes a named ``compile`` event
    with the call's wall time to every live telemetry — plus the program's
    analytic cost (`telemetry.profiling.jit_cost_fields`: FLOPs and HBM
    bytes from the re-lowered HLO's cost analysis; no second backend
    compile — memory footprints are the opt-in ``SC_COST_CAPTURE=full``
    depth), so the perf-attribution report can put every entry point on
    the roofline. Also bumps a ``dispatch.<name>`` counter —
    the per-entry-point step totals `run_end` reports. Attribute access
    (``.lower``, …) passes through to the jit object, so AOT-lowering tests
    keep working on wrapped steps.
    """

    __slots__ = ("_fn", "_name")

    def __init__(self, name: str, fn: Callable):
        self._fn = fn
        self._name = name

    def __call__(self, *args, **kwargs):
        if not _ACTIVE:
            return self._fn(*args, **kwargs)
        size = getattr(self._fn, "_cache_size", None)
        before = size() if size is not None else -1
        t0 = time.perf_counter()
        out = self._fn(*args, **kwargs)
        dt = time.perf_counter() - t0
        for t in list(_ACTIVE):
            t.counter_inc(f"dispatch.{self._name}")
        if size is not None and size() > before:
            # once per compile, never per dispatch: re-lower through jax's
            # lowering cache and read the HLO cost analysis (best-effort —
            # None on backends/signatures that refuse; no backend compile
            # at the default capture depth)
            try:
                from sparse_coding__tpu.telemetry.profiling import jit_cost_fields

                cost = jit_cost_fields(self._fn, args, kwargs)
            except Exception:
                cost = None
            for t in list(_ACTIVE):
                t.compile(self._name, dt, cost=cost)
        return out

    def __getattr__(self, attr):
        return getattr(self._fn, attr)


def tracked_jit(name: str, fn: Callable) -> Callable:
    """Wrap a jitted callable so its compiles surface as named telemetry
    events. Near-zero overhead when no RunTelemetry is live."""
    return _TrackedJit(name, fn)
