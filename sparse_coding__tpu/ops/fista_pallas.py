"""Pallas TPU kernel for the FISTA inner loop.

The fork's hot inner loop (SURVEY.md §3.2): ~500 iterations of two matmuls
over the same operands (`fista.py:116-125`). Under plain jit, each iteration's
residual/code tensors round-trip HBM; the arithmetic intensity is low enough
that HBM bandwidth, not the MXU, bounds throughput. This kernel runs the
ENTIRE iteration loop with every operand pinned in VMEM:

  grid over batch tiles (code rows are independent across examples);
  per tile: X [Tb, d], D [n, d], and the evolving codes A/A_y [Tb, n] stay
  resident in VMEM for all `num_iter` iterations — HBM is touched once on
  the way in and once on the way out.

VMEM budget (fp32): Tb·(2n + d) + n·d floats. With Tb=256, n=4096, d=512:
~10.5 MB — inside the ~16 MB/core budget; `batch_tile` shrinks for bigger
dictionaries.

`fista_pallas` matches `models.fista.fista` numerics (same update order); the
test suite asserts agreement in interpret mode, and the train loop's FISTA
decoder update (`train.loop.make_fista_decoder_update`) dispatches here
automatically on TPU.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _fista_loop(x, d, eta, l1, c0, num_iter: int, tol: float):
    """The in-VMEM FISTA iteration shared by both kernels: the kernels' own
    matmul idiom (VMEM `jnp.dot` with f32 accumulation) plugged into the ONE
    shared scaffold `models.fista.run_fista_iterations`, so the early-exit
    criterion (VERDICT r4 next #4; the reference runs a blind fixed 500,
    `fista.py:116`) cannot drift between the XLA and Pallas paths."""
    from sparse_coding__tpu.models.fista import run_fista_iterations

    def update(ahat, ahat_y, tk):
        tk_n = (1.0 + jnp.sqrt(1.0 + 4.0 * tk**2)) / 2.0
        res = x - jnp.dot(ahat_y, d, preferred_element_type=jnp.float32)
        ahat_y = ahat_y + eta * jnp.dot(res, d.T, preferred_element_type=jnp.float32)
        ahat_new = jnp.maximum(ahat_y - eta * l1, 0.0)
        ahat_y = ahat_new + (ahat_new - ahat) * ((tk - 1.0) / tk_n)
        return ahat_new, ahat_y, tk_n

    return run_fista_iterations(update, c0, num_iter, tol, eta)


def _fista_kernel(
    eta_ref, l1_ref, x_ref, d_ref, c0_ref, a_out_ref, *, num_iter: int, tol: float
):
    """One batch tile: full FISTA loop in VMEM.

    eta/l1 arrive via scalar prefetch (SMEM); x_ref [Tb, d], d_ref [n, d],
    c0_ref [Tb, n] warm-start codes, a_out_ref [Tb, n].
    """
    c0 = c0_ref[:].astype(jnp.float32)
    a_out_ref[:] = _fista_loop(
        x_ref[:], d_ref[:], eta_ref[0], l1_ref[0], c0, num_iter, tol
    )


@partial(
    jax.jit,
    static_argnames=("num_iter", "batch_tile", "interpret", "tol"),
)
def fista_pallas(
    batch: jax.Array,
    learned_dict: jax.Array,
    l1_coef,
    num_iter: int = 500,
    eta: Optional[jax.Array] = None,
    coefficients: Optional[jax.Array] = None,
    batch_tile: int = 256,
    interpret: bool = False,
    tol: float = 0.0,
) -> Tuple[jax.Array, jax.Array]:
    """Non-negative FISTA codes via the VMEM-resident kernel.

    Same contract as `models.fista.fista`: `coefficients` warm-start the
    solve (None → zeros). Returns (ahat, residual). Composes with `vmap`
    (the ensemble axis becomes an extra grid dimension).
    """
    from sparse_coding__tpu.models.fista import power_iteration_max_eig

    if eta is None:
        eta = 1.0 / (1.05 * power_iteration_max_eig(learned_dict, n_iter=50))
    B, d = batch.shape
    n = learned_dict.shape[0]
    tile = min(batch_tile, B)
    pad = (-B) % tile
    x = jnp.pad(batch, ((0, pad), (0, 0))) if pad else batch
    c0 = (
        jnp.zeros((x.shape[0], n), jnp.float32)
        if coefficients is None
        else jnp.pad(coefficients.astype(jnp.float32), ((0, pad), (0, 0)))
        if pad
        else coefficients.astype(jnp.float32)
    )

    grid = (x.shape[0] // tile,)
    ahat = pl.pallas_call(
        partial(_fista_kernel, num_iter=num_iter, tol=tol),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=grid,
            in_specs=[
                pl.BlockSpec((tile, d), lambda i, *_: (i, 0)),
                pl.BlockSpec((n, d), lambda i, *_: (0, 0)),
                pl.BlockSpec((tile, n), lambda i, *_: (i, 0)),
            ],
            out_specs=pl.BlockSpec((tile, n), lambda i, *_: (i, 0)),
        ),
        out_shape=jax.ShapeDtypeStruct((x.shape[0], n), jnp.float32),
        interpret=interpret,
    )(
        jnp.asarray(eta, jnp.float32).reshape(1),
        jnp.asarray(l1_coef, jnp.float32).reshape(1),
        x.astype(jnp.float32),
        learned_dict.astype(jnp.float32),
        c0,
    )
    ahat = ahat[:B].astype(batch.dtype)
    res = batch - ahat @ learned_dict
    return ahat, res


def _fista_kernel_hbm_dict(
    eta_ref, l1_ref, x_ref, d_hbm_ref, c0_ref, a_out_ref, d_vmem, sem,
    *, num_iter: int, tol: float
):
    """Batch-tiled FISTA with the dictionary DMA'd HBM→VMEM ONCE.

    The v1 kernel (`_fista_kernel`) lets the pipeline double-buffer every
    input block; at bench shape (n=4096, d=512) the [n, d] dictionary alone
    then costs 2x8 MB of VMEM and the kernel stops fitting (the 3.2x-slower
    XLA fallback at 2048x4096x512, round 2). Here the dictionary arrives as
    an ANY/HBM ref, is copied into a SINGLE VMEM scratch on the first grid
    step, and persists across batch tiles (the TPU grid is sequential), so
    only the small per-tile x/c0/out blocks are double-buffered.
    """
    @pl.when(pl.program_id(0) == 0)
    def _copy_dict():
        pltpu.make_async_copy(d_hbm_ref, d_vmem, sem).start()
        pltpu.make_async_copy(d_hbm_ref, d_vmem, sem).wait()

    c0 = c0_ref[:].astype(jnp.float32)
    a_out_ref[:] = _fista_loop(
        x_ref[:], d_vmem[:], eta_ref[0], l1_ref[0], c0, num_iter, tol
    )


@partial(jax.jit, static_argnames=("num_iter", "batch_tile", "interpret", "tol"))
def fista_pallas_hbm_dict(
    batch: jax.Array,
    learned_dict: jax.Array,
    l1_coef,
    num_iter: int = 500,
    eta: Optional[jax.Array] = None,
    coefficients: Optional[jax.Array] = None,
    batch_tile: int = 128,
    interpret: bool = False,
    tol: float = 0.0,
) -> Tuple[jax.Array, jax.Array]:
    """`fista_pallas` for dictionaries too big to double-buffer (see
    `_fista_kernel_hbm_dict`). Same contract and numerics."""
    from sparse_coding__tpu.models.fista import power_iteration_max_eig

    if eta is None:
        eta = 1.0 / (1.05 * power_iteration_max_eig(learned_dict, n_iter=50))
    B, d = batch.shape
    n = learned_dict.shape[0]
    tile = min(batch_tile, B)
    pad = (-B) % tile
    x = jnp.pad(batch, ((0, pad), (0, 0))) if pad else batch
    c0 = (
        jnp.zeros((x.shape[0], n), jnp.float32)
        if coefficients is None
        else jnp.pad(coefficients.astype(jnp.float32), ((0, pad), (0, 0)))
        if pad
        else coefficients.astype(jnp.float32)
    )

    grid = (x.shape[0] // tile,)
    ahat = pl.pallas_call(
        partial(_fista_kernel_hbm_dict, num_iter=num_iter, tol=tol),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=grid,
            in_specs=[
                pl.BlockSpec((tile, d), lambda i, *_: (i, 0)),
                pl.BlockSpec(memory_space=pl.ANY),
                pl.BlockSpec((tile, n), lambda i, *_: (i, 0)),
            ],
            out_specs=pl.BlockSpec((tile, n), lambda i, *_: (i, 0)),
            scratch_shapes=[
                pltpu.VMEM((n, d), jnp.float32),
                pltpu.SemaphoreType.DMA(()),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((x.shape[0], n), jnp.float32),
        interpret=interpret,
    )(
        jnp.asarray(eta, jnp.float32).reshape(1),
        jnp.asarray(l1_coef, jnp.float32).reshape(1),
        x.astype(jnp.float32),
        learned_dict.astype(jnp.float32),
        c0,
    )
    ahat = ahat[:B].astype(batch.dtype)
    res = batch - ahat @ learned_dict
    return ahat, res


def on_tpu() -> bool:
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:
        return False


# scoped-VMEM budget for auto-selection, with a 2x margin for the compiler's
# pipeline double-buffering (measured: the nominal-10.5MB 2048x4096x512 config
# actually allocates 28MB scoped and OOMs the 16MB core)
PALLAS_VMEM_BUDGET = 12 * 1024**2


def pallas_fits(batch: int, n_dict: int, d_act: int, batch_tile: int = 256) -> bool:
    """Whether the fully-VMEM-resident v1 kernel fits at this shape (every
    block double-buffered by the pipeline, dictionary included)."""
    bt = min(batch_tile, batch)
    resident = 4 * (n_dict * d_act + 3 * bt * n_dict + 2 * bt * d_act)
    return 2 * resident <= PALLAS_VMEM_BUDGET


def pallas_hbm_dict_fits(batch: int, n_dict: int, d_act: int, batch_tile: int = 128) -> bool:
    """Whether the v2 kernel (dictionary in a SINGLE VMEM scratch, only the
    small per-tile blocks double-buffered) fits. Covers the bench shape
    2048x4096x512 that v1 rejects."""
    bt = min(batch_tile, batch)
    resident = 4 * (
        n_dict * d_act          # dictionary scratch, single-buffered
        + 3 * bt * n_dict       # fori carry (ahat, ahat_y) + update temp
        + 2 * (2 * bt * n_dict + bt * d_act)  # double-buffered c0/out/x tiles
    )
    return resident <= 14 * 1024**2


def fista_solve(
    batch: jax.Array,
    learned_dict: jax.Array,
    l1_coef,
    coefficients: Optional[jax.Array],
    num_iter: int = 500,
    tol: float = 0.0,
) -> Tuple[jax.Array, jax.Array]:
    """Shape-aware FISTA: the VMEM kernel where it fits (small dictionaries —
    HBM-bound under plain jit), the XLA `fori_loop` otherwise (large shapes —
    full-batch matmuls keep the MXU fed). Same contract as `models.fista.fista`.

    ``tol > 0`` solves to convergence (early exit when the largest
    per-element code change of an iteration falls below ``tol * eta``),
    bounded by ``num_iter`` — measured-equivalent codes at tol=1e-3 with the
    converged tail skipped (THROUGHPUT §r5). ``tol=0`` is the reference's
    blind fixed-iteration semantics."""
    from sparse_coding__tpu.models.fista import fista

    B, D = batch.shape
    N = learned_dict.shape[0]
    if on_tpu() and pallas_fits(B, N, D):
        return fista_pallas(
            batch, learned_dict, l1_coef, num_iter=num_iter,
            coefficients=coefficients, tol=tol,
        )
    if on_tpu() and pallas_hbm_dict_fits(B, N, D):
        return fista_pallas_hbm_dict(
            batch, learned_dict, l1_coef, num_iter=num_iter,
            coefficients=coefficients, tol=tol,
        )
    if coefficients is None:
        coefficients = jnp.zeros((B, N), batch.dtype)
    return fista(batch, learned_dict, l1_coef, coefficients, num_iter, tol=tol)
