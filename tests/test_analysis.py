"""sclint: engine, rules, contracts, and the CI gate (tier-1).

Three layers, mirroring the package:

- per-rule pins: each seeded fixture (`tests/analysis_fixtures/scNNN_bad.py`)
  must produce exactly its rule at the `# VIOLATION`-marked line via the real
  CLI (exit 1); each clean twin must exit 0 — so a rule can neither go blind
  nor start crying wolf without a test moving;
- engine semantics: suppression comments, baseline round-trip, --json,
  exit codes (including 3 = no files);
- the gate itself: the shipped tree (`sparse_coding__tpu/ scripts/ bench.py`)
  is pinned clean, the abstract contracts pass with 100% partition coverage,
  and the mirrored Prometheus sanitizer is pinned against the real
  `telemetry.metrics_http` regex so the two cannot drift.
"""

import json
import subprocess
import sys
from pathlib import Path

import pytest

from sparse_coding__tpu.analysis import lint_paths, load_baseline
from sparse_coding__tpu.analysis.engine import write_baseline
from sparse_coding__tpu.analysis.rules import RULES

pytestmark = pytest.mark.analysis

REPO = Path(__file__).resolve().parents[1]
FIXTURES = REPO / "tests" / "analysis_fixtures"
ALL_RULES = ("SC001", "SC002", "SC003", "SC004", "SC005", "SC006", "SC007")


def run_cli(*args):
    return subprocess.run(
        [sys.executable, "-m", "sparse_coding__tpu.analysis", *args],
        capture_output=True, text=True, cwd=REPO,
    )


def violation_lines(path: Path):
    return [
        i for i, line in enumerate(path.read_text().splitlines(), start=1)
        if "# VIOLATION" in line
    ]


# -- per-rule pins -------------------------------------------------------------

@pytest.mark.parametrize("rule_id", ALL_RULES)
def test_seeded_violation_fires_with_correct_rule_and_line(rule_id):
    bad = FIXTURES / f"{rule_id.lower()}_bad.py"
    expected = violation_lines(bad)
    assert expected, f"{bad} has no # VIOLATION marker"

    proc = run_cli(str(bad))
    assert proc.returncode == 1, proc.stdout + proc.stderr
    findings, _ = lint_paths([bad])
    assert sorted({f.rule for f in findings}) == [rule_id]
    assert sorted({f.line for f in findings}) == expected


@pytest.mark.parametrize("rule_id", ALL_RULES)
def test_clean_twin_is_silent(rule_id):
    clean = FIXTURES / f"{rule_id.lower()}_clean.py"
    proc = run_cli(str(clean))
    assert proc.returncode == 0, proc.stdout + proc.stderr
    findings, n = lint_paths([clean])
    assert findings == [] and n == 1


def test_rule_registry_is_complete():
    assert tuple(sorted(RULES)) == ALL_RULES
    for spec in RULES.values():
        assert spec.doc, f"{spec.id} has no docstring"
        assert spec.scope in ("module", "repo")


# -- engine semantics ----------------------------------------------------------

def test_suppression_comment_forms(tmp_path):
    # inline, statement-first-line, and preceding-comment-line forms all
    # sanction exactly the named rule
    src = tmp_path / "mod.py"
    src.write_text(
        '__sclint_hot_entries__ = ("f",)\n'
        "def f(out):\n"
        "    a = out.sum().item()  # sclint: allow(SC003) inline\n"
        "    # sclint: allow(SC003) preceding comment line\n"
        "    b = out.mean().item()\n"
        "    c = out.max().item()\n"
        "    return a + b + c\n"
    )
    findings, _ = lint_paths([src])
    assert [f.rule for f in findings] == ["SC003"]
    assert findings[0].line == 6  # only the unsanctioned sync survives


def test_wrong_rule_in_allow_comment_does_not_suppress(tmp_path):
    src = tmp_path / "mod.py"
    src.write_text(
        '__sclint_hot_entries__ = ("f",)\n'
        "def f(out):\n"
        "    return out.sum().item()  # sclint: allow(SC001) wrong rule\n"
    )
    findings, _ = lint_paths([src])
    assert [f.rule for f in findings] == ["SC003"]


def test_baseline_round_trip_and_gate_on_new_findings(tmp_path):
    bad = FIXTURES / "sc001_bad.py"
    findings, _ = lint_paths([bad])
    assert findings

    baseline_file = tmp_path / "baseline.json"
    write_baseline(baseline_file, findings)
    keys = load_baseline(baseline_file)
    assert keys == {f.key for f in findings}

    # grandfathered: the same findings are dropped
    after, _ = lint_paths([bad], baseline=keys)
    assert after == []

    # but a *different* finding still fails the gate
    other, _ = lint_paths([FIXTURES / "sc004_bad.py"], baseline=keys)
    assert [f.rule for f in other] == ["SC004"]


def test_cli_baseline_flag_round_trip(tmp_path):
    bad = FIXTURES / "sc002_bad.py"
    baseline_file = tmp_path / "baseline.json"

    wrote = run_cli(str(bad), "--write-baseline", str(baseline_file))
    assert wrote.returncode == 0, wrote.stdout + wrote.stderr
    assert baseline_file.exists()

    gated = run_cli(str(bad), "--baseline", str(baseline_file))
    assert gated.returncode == 0, gated.stdout + gated.stderr


def test_cli_json_output():
    proc = run_cli(str(FIXTURES / "sc005_bad.py"), "--json")
    assert proc.returncode == 1
    doc = json.loads(proc.stdout)
    assert doc["files_scanned"] == 1
    (finding,) = doc["findings"]
    assert finding["rule"] == "SC005"
    assert finding["key"].startswith("SC005:")
    assert finding["path"].endswith("sc005_bad.py")


def test_cli_exit_3_when_no_files(tmp_path):
    proc = run_cli(str(tmp_path))
    assert proc.returncode == 3


def test_cli_select_limits_rules():
    bad = FIXTURES / "sc006_bad.py"
    assert run_cli(str(bad), "--select", "SC006").returncode == 1
    assert run_cli(str(bad), "--select", "SC001").returncode == 0
    assert run_cli(str(bad), "--select", "SC999").returncode == 2


def test_syntax_error_becomes_sc000(tmp_path):
    src = tmp_path / "broken.py"
    src.write_text("def f(:\n")
    findings, n = lint_paths([src])
    assert n == 1
    assert [f.rule for f in findings] == ["SC000"]


# -- registry mirrors cannot drift ---------------------------------------------

def test_sanitize_metric_pinned_against_metrics_http():
    from sparse_coding__tpu.analysis.context import RepoContext
    from sparse_coding__tpu.telemetry import metrics_http

    for name in (
        "serve.queue.depth", "a b/c-d", "slo:window", "weirdéname", "ok_1",
    ):
        assert RepoContext.sanitize_metric(name) == metrics_http._NAME_RE.sub(
            "_", name
        )


def test_span_tables_match_real_module():
    from sparse_coding__tpu.analysis.context import RepoContext
    from sparse_coding__tpu.telemetry import spans

    t = RepoContext().span_tables
    assert t["GOODPUT_CATEGORIES"] == spans.GOODPUT_CATEGORIES
    assert t["BADPUT_CATEGORIES"] == spans.BADPUT_CATEGORIES
    assert t["DERIVED_CATEGORIES"] == spans.DERIVED_CATEGORIES
    assert t["INNER_CATEGORIES"] == spans.INNER_CATEGORIES


# -- the gate ------------------------------------------------------------------

def test_shipped_tree_is_clean():
    """The acceptance gate: the CLI exits 0 over the shipped tree. Any new
    finding must be fixed or explicitly sanctioned in-diff — there is no
    baseline file in CI."""
    proc = run_cli("sparse_coding__tpu/", "scripts/", "bench.py")
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_contracts_pass_with_full_partition_coverage():
    from sparse_coding__tpu.analysis.contracts import run_contracts

    results = {c.name: c for c in run_contracts()}
    assert set(results) == {"partition-coverage", "span-tables", "flags-docs"}
    for c in results.values():
        assert c.ok, c.render()
    cov = results["partition-coverage"].summary
    n, total = cov.split(" ")[0].split("/")
    assert n == total, cov  # 100% leaf coverage


def test_flag_registry_covers_all_env_reads():
    """Every SC_* os.environ read in the tree goes through utils/flags.py —
    i.e. SC005 over the package, scripts, bench AND tests is silent."""
    findings, _ = lint_paths(
        [REPO / "sparse_coding__tpu", REPO / "scripts", REPO / "bench.py",
         REPO / "tests" / "_multiprocess_worker.py"],
        select={"SC005"},
    )
    assert findings == []
