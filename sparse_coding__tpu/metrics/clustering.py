"""Dictionary-vector clustering diagnostics (host-side sklearn/scipy).

Counterpart of the reference `standard_metrics.py:532-577`: t-SNE + KMeans
cluster listing and hierarchical (cosine-linkage) clustering. Offline
analysis — numpy in, numpy out.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np


def cluster_vectors(
    model,
    n_clusters: int = 1000,
    top_clusters: int = 10,
    save_loc: Optional[str] = None,
    random_state: int = 0,
    perplexity: float = 30.0,
) -> List[np.ndarray]:
    """t-SNE → KMeans on the dictionary rows; returns the member indices of
    the `top_clusters` most populous clusters
    (reference `cluster_vectors`, `standard_metrics.py:533-566`)."""
    from sklearn.cluster import KMeans
    from sklearn.manifold import TSNE

    vectors = np.asarray(model.get_learned_dict())
    perplexity = min(perplexity, max(2.0, (vectors.shape[0] - 1) / 3))
    tsne = TSNE(n_components=2, random_state=random_state, perplexity=perplexity)
    embedded = tsne.fit_transform(vectors)
    n_clusters = min(n_clusters, vectors.shape[0])
    kmeans = KMeans(n_clusters=n_clusters, random_state=random_state, n_init=10).fit(embedded)
    ids, counts = np.unique(kmeans.labels_, return_counts=True)
    order = np.argsort(counts)[::-1]
    top = [np.where(kmeans.labels_ == ids[i])[0] for i in order[:top_clusters]]
    if save_loc:
        with open(save_loc, "w") as f:
            for cluster in top:
                f.write(f"{list(cluster)}\n")
    return top


def hierarchical_cluster_vectors(vectors, n_clusters: int = 100) -> np.ndarray:
    """Average-linkage cosine hierarchical clustering; returns cluster ids per
    row (reference `hierarchical_cluster_vectors`, `standard_metrics.py:568-577`,
    minus the interactive dendrogram display)."""
    from scipy.cluster.hierarchy import cut_tree, linkage

    linkage_matrix = linkage(np.asarray(vectors), "average", metric="cosine")
    return cut_tree(linkage_matrix, n_clusters=n_clusters).reshape(-1)
