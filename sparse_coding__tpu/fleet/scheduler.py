"""Fleet scheduler: pack members into items, reassign dead work, verify done.

``python -m sparse_coding__tpu.fleet.scheduler <fleet_dir>`` is the one
process per fleet that owns *liveness*: workers pull work themselves
(`fleet.worker`), so all the scheduler does on each tick is

  1. **reap expired leases** (`WorkQueue.reap_expired`) — items whose
     holder stopped heartbeating go back to `pending/` with their lineage
     recording who lost them; repeat offenders are quarantined after
     ``--quarantine-after`` strikes so reassignment flows to healthy
     workers instead of crash-looping on a sick host;
  2. **re-verify done items** — every newly done item's learned-dict
     export must match its size/digest manifest (`fleet.worker.
     verify_export`), and ALL done exports are re-verified once more
     before the fleet declares success; post-completion corruption (bit
     rot, a partial overwrite) sends the item back to `pending/` for
     retraining;
  3. emit the reassignment/quarantine/lost events `fleet.report` and the
     monitor's fleet view render.

Packing (`pack_members`) sizes the member groups from HBM-watermark data:
`member_bytes_from_run` reads the ``hbm.*.peak_bytes_in_use`` gauges a
previous run's telemetry recorded (`telemetry.profiling.
record_hbm_watermarks`) and divides by that run's member count — the
empirical per-member footprint, optimizer moments and XLA temps included,
which no analytic estimate gets right. Groups fill a worker's HBM budget
minus a safety reserve; a thousand-member sweep becomes however many items
the fleet's chips can actually hold.
"""

from __future__ import annotations

import argparse
import math
import sys
import time
from typing import Any, Dict, List, Optional, Sequence

from sparse_coding__tpu.fleet.queue import WorkQueue

__all__ = [
    "FleetScheduler",
    "build_sweep_items",
    "member_bytes_from_run",
    "pack_members",
    "main",
]


# -- HBM-aware packing ---------------------------------------------------------

def member_bytes_from_run(run_dir, n_members: int) -> Optional[float]:
    """Empirical per-member HBM footprint from a prior run's watermark
    gauges: max ``hbm.*.peak_bytes_in_use`` across devices / members
    trained. None when the run recorded no watermarks."""
    from sparse_coding__tpu.telemetry.report import _merged_gauges, load_run

    run = load_run(run_dir)
    peaks = [
        v for k, v in _merged_gauges(run).items()
        if k.startswith("hbm.") and k.endswith(".peak_bytes_in_use")
    ]
    if not peaks or n_members <= 0:
        return None
    return max(peaks) / float(n_members)


def pack_members(
    members: Sequence[Any],
    bytes_per_member: Optional[float] = None,
    hbm_budget_bytes: Optional[float] = None,
    reserve_fraction: float = 0.2,
    max_members_per_item: Optional[int] = None,
    watermark_run_dir=None,
    watermark_members: Optional[int] = None,
) -> List[List[Any]]:
    """Split `members` into contiguous groups that fit one worker's HBM.

    Group size = the largest count whose summed per-member bytes fits
    ``hbm_budget_bytes * (1 - reserve_fraction)`` (the reserve absorbs XLA
    temp spikes the watermark undersells), clamped by
    ``max_members_per_item``. With no sizing information everything lands
    in one item. ``watermark_run_dir`` + ``watermark_members`` derive
    ``bytes_per_member`` from a previous run's recorded HBM peaks."""
    members = list(members)
    if not members:
        return []
    if bytes_per_member is None and watermark_run_dir is not None:
        bytes_per_member = member_bytes_from_run(
            watermark_run_dir, watermark_members or len(members)
        )
    size = len(members)
    if bytes_per_member and hbm_budget_bytes:
        usable = hbm_budget_bytes * (1.0 - reserve_fraction)
        size = max(1, int(math.floor(usable / bytes_per_member)))
    if max_members_per_item is not None:
        size = max(1, min(size, int(max_members_per_item)))
    return [members[i : i + size] for i in range(0, len(members), size)]


def build_sweep_items(
    queue: WorkQueue,
    groups: Sequence[Sequence[float]],
    base_kwargs: Dict[str, Any],
    driver: str = "basic_l1_sweep",
    name_prefix: str = "g",
) -> List[Dict[str, Any]]:
    """Submit one work item per member group of an l1 sweep. Each item's
    payload is the full driver invocation (`fleet.worker.run_item`), so an
    item is self-contained — any worker can run it with nothing but the
    queue directory."""
    items = []
    for i, group in enumerate(groups):
        l1s = [float(a) for a in group]
        items.append(
            queue.submit(
                f"{name_prefix}{i}",
                members=[f"l1_{a:.2e}" for a in l1s],
                payload={"driver": driver,
                         "kwargs": {**base_kwargs, "l1_values": l1s}},
            )
        )
    return items


# -- the scheduler loop --------------------------------------------------------

class FleetScheduler:
    """Owns reaping, quarantine, and done-export re-verification for one
    fleet directory (see module docstring)."""

    def __init__(
        self,
        fleet_dir,
        lease_seconds: float = 30.0,
        max_attempts: Optional[int] = 5,
        quarantine_after: Optional[int] = 3,
        verify_done: bool = True,
        telemetry=None,
    ):
        self.queue = WorkQueue(fleet_dir)
        self.lease_seconds = float(lease_seconds)
        self.max_attempts = max_attempts
        self.quarantine_after = quarantine_after
        self.verify_done = verify_done
        self.telemetry = telemetry
        self._verified_done: set = set()

    def _event(self, etype: str, **fields):
        if self.telemetry is not None:
            self.telemetry.event(etype, **fields)
            if etype in ("lease_expired", "quarantine", "item_lost",
                         "export_corrupt"):
                self.telemetry.counter_inc(f"fleet.{etype}")

    def _verify_done_items(self, actions: List[Dict[str, Any]]) -> None:
        from sparse_coding__tpu.fleet.worker import verify_export

        for item in self.queue.items("done"):
            item_id = item["item"]
            if item_id in self._verified_done:
                continue
            ok, reason = verify_export(self.queue.run_dir(item_id))
            if ok:
                self._verified_done.add(item_id)
                continue
            # post-completion corruption: the member is NOT done — requeue
            # for retraining rather than report a dict nobody can load.
            # Same attempt budget as every other requeue: a disk that rots
            # every export must eventually count the members LOST, not
            # cycle done→pending forever
            moved = self.queue.requeue_done(
                item_id, "export_corrupt", reason, self.max_attempts
            )
            if moved is None:
                continue
            bucket, rec = moved
            actions.append({"kind": "export_corrupt", "item": item_id,
                            "reason": reason, "requeued_to": bucket})
            self._event("export_corrupt", item=item_id, reason=reason,
                        requeued_to=bucket)
            if bucket == "failed":
                actions.append({"kind": "item_lost", "item": item_id,
                                "members": rec.get("members", []),
                                "attempts": rec["attempt"]})
                self._event("item_lost", item=item_id,
                            members=rec.get("members", []),
                            attempts=rec["attempt"])

    def tick(self, now: Optional[float] = None) -> List[Dict[str, Any]]:
        """One maintenance pass; returns the action records (tests assert
        on them, the CLI loop logs them)."""
        actions = self.queue.reap_expired(
            now=now,
            max_attempts=self.max_attempts,
            quarantine_after=self.quarantine_after,
            grace_seconds=self.lease_seconds,
            on_event=lambda kind, fields: self._event(kind, **fields),
        )
        if self.verify_done:
            self._verify_done_items(actions)
        return actions

    def run(
        self,
        poll_every: float = 2.0,
        exit_when_done: bool = True,
        max_seconds: Optional[float] = None,
    ) -> Dict[str, Any]:
        """Tick until the queue finishes (every item done or failed).
        Returns the final `WorkQueue.state()`."""
        t0 = time.time()
        while True:
            self.tick()
            if exit_when_done and self.queue.finished():
                # per-tick verification only checks NEWLY done items (the
                # cache keeps ticks cheap); before declaring success,
                # re-verify every export once — corruption found here
                # requeues the item and the fleet keeps running
                self._verified_done.clear()
                if not self.tick() and self.queue.finished():
                    break
            if max_seconds is not None and time.time() - t0 >= max_seconds:
                break
            time.sleep(poll_every)
        state = self.queue.state()
        self._event(
            "fleet_done",
            items=state["item_counts"],
            members=state["members"],
        )
        return state


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m sparse_coding__tpu.fleet.scheduler",
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument("fleet_dir", help="fleet root (holds queue/ and runs/)")
    ap.add_argument("--lease-seconds", type=float, default=30.0,
                    help="grace given to claim-without-lease orphans "
                    "(workers choose their own lease length at claim time)")
    ap.add_argument("--poll", type=float, default=2.0,
                    help="tick period in seconds (default 2)")
    ap.add_argument("--max-attempts", type=int, default=5,
                    help="per-item attempt budget before it counts as lost")
    ap.add_argument("--quarantine-after", type=int, default=3,
                    help="strikes (lost leases) before a worker is excluded")
    ap.add_argument("--max-seconds", type=float, default=None,
                    help="stop ticking after this long even if unfinished")
    ap.add_argument("--no-verify-done", action="store_true",
                    help="skip re-verifying done items' export manifests")
    args = ap.parse_args(argv)

    from sparse_coding__tpu.telemetry import RunTelemetry

    telemetry = RunTelemetry(
        out_dir=args.fleet_dir,
        run_name="fleet_scheduler",
        config={"lease_seconds": args.lease_seconds,
                "max_attempts": args.max_attempts,
                "quarantine_after": args.quarantine_after},
        file_name="scheduler_events.jsonl",
    )
    telemetry.run_start()
    sched = FleetScheduler(
        args.fleet_dir,
        lease_seconds=args.lease_seconds,
        max_attempts=args.max_attempts,
        quarantine_after=args.quarantine_after,
        verify_done=not args.no_verify_done,
        telemetry=telemetry,
    )
    status = "ok"
    try:
        state = sched.run(poll_every=args.poll, max_seconds=args.max_seconds)
        m = state["members"]
        outstanding = (
            state["item_counts"]["pending"] + state["item_counts"]["leased"]
        )
        print(
            f"[fleet] items {state['item_counts']}; members "
            f"{m['done']} done / {m['lost']} lost"
            + (f" / {outstanding} item(s) UNFINISHED (timed out)"
               if outstanding else "")
        )
        # success = the sweep actually finished with nothing lost; a
        # --max-seconds timeout with work outstanding is NOT success
        ok = (
            m["lost"] == 0
            and state["item_counts"]["failed"] == 0
            and outstanding == 0
        )
        return 0 if ok else 1
    except BaseException as e:
        status = f"error: {type(e).__name__}: {e}"
        raise
    finally:
        telemetry.close(status=status)


if __name__ == "__main__":
    sys.exit(main())
