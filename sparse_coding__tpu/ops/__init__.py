from sparse_coding__tpu.ops.fista_pallas import (
    fista_pallas,
    fista_solve,
    on_tpu,
    pallas_fits,
)
