"""Goodput/badput wall-time ledger: where did a run's seconds actually go?

`build_ledger(run_dir)` merges every ``events*.jsonl`` under a run
directory — across processes (``events.p<i>.jsonl``) AND across resume
generations (a supervised restart appends new ``run_start``/``run_end``
pairs to the same log) plus the supervisor's ``supervisor_events.jsonl`` —
and assigns every second of wall clock to exactly one category:

  goodput   ``step``            fused train-step / harvest-forward windows
  badput    ``compile``         jit compiles (tracked_jit events as spans)
            ``data_wait``       chunk reads, prefetch waits, dataset loads
            ``checkpoint``      checkpoint save/restore, export commits
            ``preempt_drain``   the preemption checkpoint before exit 75
            ``degraded_skip``   quarantined-chunk skip handling
            ``export_verify``   fleet export/admission verification
            ``restart_backoff`` supervisor backoff sleeps (from ``restart``
                                events; the supervisor's own spans confirm)
            ``preempted_down``  inter-generation downtime after a preemption
            ``reassign_gap``    fleet lease-loss → next-claim gaps (lineage)
            ``straggler_idle``  fast hosts waiting on the slowest (derived
                                from cross-host chunk skew windows)
            ``unaccounted``     the honest remainder — never guessed away

Wall time is *process-seconds*: each process's span runs from its first
``run_start`` to its last event (inter-generation gaps included); the
run's total is the sum over processes. Durations prefer monotonic-derived
fields (``seconds``, ``wall_seconds``) over wall-clock subtraction, so an
NTP step cannot mint or destroy time within a generation; inter-generation
gaps necessarily use wall timestamps (two different process lifetimes).

Spans may nest (a dispatch that compiles inside a step window, a periodic
checkpoint inside it, harvest-forward spans inside the sweep's
dataset-init wait): every covered instant is assigned to the *innermost*
active span (`_exclusive_seconds` — an exact sweep line), so nothing is
double-counted.

`to_chrome_trace(ledger)` exports the ledger as Chrome trace-event JSON —
one track per (host, generation), spans colored by category — loadable in
Perfetto / chrome://tracing. `python -m sparse_coding__tpu.timeline` is
the CLI over both (docs/observability.md §7).
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Any, Dict, List, Optional, Sequence

from sparse_coding__tpu.telemetry.multihost import (
    PROC_FILE_RE as _PROC_FILE_RE,
    chunk_skew_windows,
)
from sparse_coding__tpu.telemetry.spans import CATEGORIES, GOODPUT_CATEGORIES

__all__ = [
    "load_streams",
    "build_ledger",
    "build_ledger_from_streams",
    "fleet_reassignment_gaps",
    "to_chrome_trace",
    "render_ledger",
]

_EVENT_GLOBS = (
    "events.jsonl", "events.p*.jsonl", "*_events.jsonl", "*_events.p*.jsonl",
)
# legacy (generation-unstamped) restart records are written between the
# child's exit and the next generation's run_start, i.e. INSIDE the gap;
# this small slack only absorbs clock rounding at the edges
_RESTART_SLACK = 1.0


def _read_jsonl(path: Path) -> List[Dict[str, Any]]:
    out: List[Dict[str, Any]] = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue  # torn tail — not the ledger's problem
                if isinstance(rec, dict):
                    out.append(rec)
    except OSError:
        pass
    return out


# log streams whose lifetime OVERLAPS the driver generations they manage —
# counting them as driver wall would double every supervised second. The
# supervisor's stream still feeds `restart` records into gap classification.
_ORCH_RUN_NAME_PREFIXES = ("supervisor", "fleet_scheduler", "fleet_worker")
_ORCH_FILE_PREFIXES = ("supervisor", "scheduler_events", "worker_")


def load_streams(run_dir) -> List[Dict[str, Any]]:
    """One entry per event FILE (the per-process, per-writer unit the
    generation splitter needs — a flat cross-file merge cannot tell a
    supervisor ``run_end`` from a driver's)::

        {"file": str, "records": [...], "process_index": int,
         "supervisor": bool}

    ``supervisor`` marks *orchestration* streams (the supervisor, the fleet
    scheduler, fleet workers): their lifetimes overlap the driver
    generations they manage, so they are excluded from driver wall — but
    their ``restart`` records still classify inter-generation gaps.
    """
    d = Path(run_dir)
    if not d.is_dir():
        raise FileNotFoundError(f"run dir {d} does not exist")
    found = set()
    for pat in _EVENT_GLOBS:
        found.update(d.rglob(pat))
    streams = []
    for path in sorted(found):
        records = _read_jsonl(path)
        if not records:
            continue
        m = _PROC_FILE_RE.search(path.name)
        proc = int(m.group(1)) if m else None
        if proc is None:
            tags = [r["process_index"] for r in records if "process_index" in r]
            proc = int(tags[0]) if tags else 0
        run_names = [
            str(r.get("run_name") or "")
            for r in records if r.get("event") == "run_start"
        ]
        orchestration = path.name.startswith(_ORCH_FILE_PREFIXES) or any(
            n.startswith(_ORCH_RUN_NAME_PREFIXES) for n in run_names
        )
        streams.append({
            "file": str(path), "records": records,
            "process_index": proc, "supervisor": orchestration,
        })
    return streams


# -- generation analysis ------------------------------------------------------

def _split_generations(records: Sequence[Dict[str, Any]]) -> List[Dict[str, Any]]:
    gens: List[Dict[str, Any]] = []
    cur: Optional[Dict[str, Any]] = None
    for r in records:
        if r.get("event") == "run_start":
            cur = {"run_start": r, "records": []}
            gens.append(cur)
        else:
            if cur is None:
                # leading records without a run_start (torn head): implicit gen
                cur = {"run_start": None, "records": []}
                gens.append(cur)
            cur["records"].append(r)
    return gens


def _num(v) -> Optional[float]:
    return float(v) if isinstance(v, (int, float)) and v == v else None


def _exclusive_seconds(spans: List[Dict[str, Any]]) -> Dict[str, float]:
    """Per-category *exclusive* seconds: every instant covered by ≥1 span is
    assigned to exactly one — the innermost (latest-started; ties go to the
    shorter) active span. This is what makes nesting safe: a compile inside
    a step window counts as compile (and the step window shrinks by exactly
    that much), a harvest-forward ``step`` span inside the sweep's
    ``dataset_init`` data-wait span counts as step. A sweep line over span
    boundaries — O(n log n) with small active sets, exact for partial
    overlaps too."""
    if not spans:
        return {}
    boundary = []  # (time, 0=end first at equal times, span)
    for s in spans:
        if s["seconds"] <= 0:
            continue
        boundary.append((s["start"], 1, s))
        boundary.append((s["start"] + s["seconds"], 0, s))
    boundary.sort(key=lambda e: (e[0], e[1]))
    totals: Dict[str, float] = {}
    active: List[Dict[str, Any]] = []
    prev_t: Optional[float] = None
    for t, kind, s in boundary:
        if prev_t is not None and active and t > prev_t:
            winner = max(active, key=lambda a: (a["start"], -a["seconds"]))
            totals[winner["category"]] = (
                totals.get(winner["category"], 0.0) + (t - prev_t)
            )
        if kind == 1:
            active.append(s)
        else:
            active.remove(s)
        prev_t = t
    return totals


def _analyze_generation(gen: Dict[str, Any], idx: int) -> Dict[str, Any]:
    rs = gen["run_start"]
    records = gen["records"]
    all_ts = [t for t in (_num(r.get("ts")) for r in ([rs] if rs else []) + list(records)) if t is not None]
    start_ts = _num(rs.get("ts")) if rs else None
    if start_ts is None:
        start_ts = min(all_ts) if all_ts else 0.0
    run_end = next((r for r in reversed(records) if r.get("event") == "run_end"), None)
    end_ts = _num(run_end.get("ts")) if run_end else None
    if end_ts is None:
        end_ts = max(all_ts) if all_ts else start_ts
    end_ts = max(end_ts, start_ts)
    wall = _num(run_end.get("wall_seconds")) if run_end else None
    if wall is None:
        wall = end_ts - start_ts
    status = str(run_end.get("status", "running")) if run_end else "running"
    preempted = status.startswith("preempted") or any(
        r.get("event") == "preempt" for r in records
    )
    generation = idx
    if rs is not None and isinstance(rs.get("generation"), int):
        generation = rs["generation"]
    elif run_end is not None and isinstance(run_end.get("generation"), int):
        generation = run_end["generation"]

    spans: List[Dict[str, Any]] = []
    for r in records:
        secs = _num(r.get("seconds"))
        if secs is None:
            continue
        if r.get("event") == "span" and r.get("category") in CATEGORIES:
            start = _num(r.get("ts_start"))
            if start is None:
                start = (_num(r.get("ts")) or start_ts) - secs
            span = {
                "category": r["category"], "start": start, "seconds": secs,
                "name": r.get("name"), "source": "span",
            }
            # trace tags (telemetry.tracing): carried through so the Chrome
            # export can render a per-request track view
            for key in ("trace_id", "traces", "replica"):
                if r.get(key) is not None:
                    span[key] = r[key]
            spans.append(span)
        elif r.get("event") == "compile":
            # compile events double as spans: the tracked_jit wall time of
            # the dispatch that compiled, ending at the record's ts
            end = _num(r.get("ts")) or start_ts
            spans.append({
                "category": "compile", "start": end - secs, "seconds": secs,
                "name": r.get("name"), "source": "compile",
            })
    categories = _exclusive_seconds(spans)
    classified = sum(categories.values())
    categories["unaccounted"] = max(0.0, wall - classified)
    return {
        "generation": generation,
        "start_ts": start_ts,
        "end_ts": end_ts,
        "wall_seconds": wall,
        "status": status,
        "preempted": preempted,
        "spans": spans,
        "categories": categories,
        "overcounted_seconds": max(0.0, classified - wall),
    }


def _run_dir_matches(r: Dict[str, Any], run_dir) -> bool:
    rd = r.get("run_dir")
    if rd is None or run_dir is None:
        return True
    # resolved-path equality when the stamped dir still exists; basename as
    # the relocatable fallback (checked-in golden run dirs are read from a
    # different root than they were stamped in)
    try:
        prd, pld = Path(rd), Path(run_dir)
        return prd.resolve() == pld.resolve() or prd.name == pld.name
    except OSError:
        return True


def _match_restarts(
    restarts, used: set, run_dir, gap_lo: float, gap_hi: float,
    next_generation: Optional[int],
) -> List[Dict[str, Any]]:
    """Supervisor ``restart`` events belonging to ONE inter-generation gap.
    Preferred join: the stamped ``generation`` (of the generation the
    restart spawned) + ``run_dir`` (ISSUE 9 satellite). Unstamped legacy
    records fall back to timestamp containment — and ``used`` guarantees a
    record is consumed by at most one gap either way (short crash-loop
    generations put one restart inside several gaps' slack windows)."""
    candidates = [
        r for r in restarts
        if id(r) not in used and _run_dir_matches(r, run_dir)
    ]
    stamped = [
        r for r in candidates
        if isinstance(r.get("generation"), int)
        and r["generation"] == next_generation
    ]
    if not stamped:
        stamped = [
            r for r in candidates
            if not isinstance(r.get("generation"), int)
            and _num(r.get("ts")) is not None
            and gap_lo - _RESTART_SLACK <= r["ts"] <= gap_hi + _RESTART_SLACK
        ]
    for r in stamped:
        used.add(id(r))
    return stamped


def fleet_reassignment_gaps(fleet_dir) -> List[Dict[str, Any]]:
    """Wall time items spent between losing a lease and being re-claimed,
    from the queue's item lineage (docs/FLEET.md) — the fleet-level badput
    the per-run event logs cannot see. Empty for non-fleet directories."""
    try:
        from sparse_coding__tpu.fleet.queue import is_fleet_dir
    except ImportError:  # pragma: no cover
        return []
    if not is_fleet_dir(fleet_dir):
        return []
    gaps: List[Dict[str, Any]] = []
    queue = Path(fleet_dir) / "queue"
    for bucket in ("pending", "leased", "done", "failed"):
        for p in sorted(queue.glob(f"{bucket}/*.json")):
            try:
                with open(p) as f:
                    item = json.load(f)
            except (OSError, json.JSONDecodeError):
                continue
            lineage = item.get("lineage") or []
            for prev, nxt in zip(lineage, lineage[1:]):
                t0 = _num(prev.get("released_ts"))
                t1 = _num(nxt.get("claimed_ts"))
                if t0 is None or t1 is None or t1 <= t0:
                    continue
                gaps.append({
                    "item": item.get("item", p.stem),
                    "seconds": t1 - t0,
                    "start_ts": t0,
                    "from_worker": prev.get("worker"),
                    "to_worker": nxt.get("worker"),
                })
    return gaps


def build_ledger_from_streams(
    streams: List[Dict[str, Any]],
    run_dir=None,
    reassignment_gaps: Optional[List[Dict[str, Any]]] = None,
) -> Dict[str, Any]:
    """The ledger, from pre-loaded streams (tests) — see `build_ledger`."""
    driver_streams = [s for s in streams if not s["supervisor"]]
    restarts = [
        r
        for s in streams if s["supervisor"]
        for r in s["records"] if r.get("event") == "restart"
    ]

    categories: Dict[str, float] = {}
    spans_out: List[Dict[str, Any]] = []
    processes: Dict[int, Dict[str, Any]] = {}
    n_generations = 0
    used_restarts: set = set()  # each restart record joins at most one gap

    def add(cat: str, secs: float, proc: int):
        categories[cat] = categories.get(cat, 0.0) + secs
        pcat = processes[proc]["categories"]
        pcat[cat] = pcat.get(cat, 0.0) + secs

    for stream in driver_streams:
        proc = int(stream["process_index"])
        pstate = processes.setdefault(proc, {
            "wall_seconds": 0.0, "categories": {}, "generations": [],
        })
        gens = [
            _analyze_generation(g, i)
            for i, g in enumerate(_split_generations(stream["records"]))
        ]
        gens = [g for g in gens if g["wall_seconds"] > 0 or g["spans"]]
        n_generations += len(gens)
        for g in gens:
            pstate["wall_seconds"] += g["wall_seconds"]
            pstate["generations"].append({
                "generation": g["generation"], "status": g["status"],
                "wall_seconds": round(g["wall_seconds"], 3),
                "start_ts": g["start_ts"], "end_ts": g["end_ts"],
            })
            for cat, secs in g["categories"].items():
                add(cat, secs, proc)
            for s in g["spans"]:
                spans_out.append({
                    **s, "process_index": proc, "generation": g["generation"],
                })
        # inter-generation gaps: restart backoff (from the supervisor's
        # stamped restart events) + post-preemption downtime
        for cur, nxt in zip(gens, gens[1:]):
            gap = nxt["start_ts"] - cur["end_ts"]
            if gap <= 0:
                continue
            pstate["wall_seconds"] += gap
            backoff = 0.0
            for r in _match_restarts(
                restarts, used_restarts, run_dir, cur["end_ts"],
                nxt["start_ts"], nxt["generation"],
            ):
                backoff += _num(r.get("backoff_seconds")) or 0.0
            backoff = min(backoff, gap)
            rest = gap - backoff
            down_cat = "preempted_down" if cur["preempted"] else "unaccounted"
            if rest > 0:
                add(down_cat, rest, proc)
                spans_out.append({
                    "category": down_cat, "start": cur["end_ts"],
                    "seconds": rest, "name": "inter-generation downtime",
                    "process_index": proc, "generation": cur["generation"],
                    "derived": True,
                })
            if backoff > 0:
                add("restart_backoff", backoff, proc)
                spans_out.append({
                    "category": "restart_backoff",
                    "start": nxt["start_ts"] - backoff, "seconds": backoff,
                    "name": "supervisor backoff",
                    "process_index": proc, "generation": cur["generation"],
                    "derived": True,
                })

    # straggler idle (pods): the faster hosts' per-window wait on the
    # slowest, shifted out of their unaccounted remainder — never invented
    # beyond what the process's own wall already contains
    all_driver_events = [r for s in driver_streams for r in s["records"]]
    idle: Dict[int, float] = {}
    for w in chunk_skew_windows(all_driver_events):
        for p, secs in w["seconds"].items():
            idle[p] = idle.get(p, 0.0) + (w["max"] - secs)
    for p, secs in idle.items():
        if p not in processes or secs <= 0:
            continue
        shift = min(secs, processes[p]["categories"].get("unaccounted", 0.0))
        if shift <= 0:
            continue
        processes[p]["categories"]["unaccounted"] -= shift
        processes[p]["categories"]["straggler_idle"] = (
            processes[p]["categories"].get("straggler_idle", 0.0) + shift
        )
        categories["unaccounted"] = categories.get("unaccounted", 0.0) - shift
        categories["straggler_idle"] = categories.get("straggler_idle", 0.0) + shift

    # fleet lease-reassignment gaps (item lineage) — fleet dirs only
    gaps = reassignment_gaps or []
    for g in gaps:
        categories["reassign_gap"] = categories.get("reassign_gap", 0.0) + g["seconds"]
        spans_out.append({
            "category": "reassign_gap", "start": g["start_ts"],
            "seconds": g["seconds"],
            "name": f"reassign {g['item']}: {g.get('from_worker')}→{g.get('to_worker')}",
            "process_index": -1, "generation": 0, "derived": True,
        })

    wall = sum(p["wall_seconds"] for p in processes.values())
    wall += sum(g["seconds"] for g in gaps)
    goodput = sum(categories.get(c, 0.0) for c in GOODPUT_CATEGORIES)
    badput = {
        c: round(s, 3) for c, s in sorted(categories.items())
        if c not in GOODPUT_CATEGORIES and s > 0
    }
    top = sorted(
        (s for s in spans_out if s["category"] not in GOODPUT_CATEGORIES),
        key=lambda s: -s["seconds"],
    )[:5]
    # legacy runs predate span instrumentation: 0 step-seconds there means
    # "not measured", never "0% goodput" — renderers and the gate key on
    # this. Compile events and derived gaps don't count: only real span
    # records prove the run was instrumented.
    has_spans = any(s.get("source") == "span" for s in spans_out)
    return {
        "run_dir": None if run_dir is None else str(run_dir),
        "has_spans": has_spans,
        "wall_seconds": round(wall, 3),
        "processes": {
            p: {
                "wall_seconds": round(st["wall_seconds"], 3),
                "categories": {k: round(v, 3) for k, v in sorted(st["categories"].items()) if v > 0},
                "generations": st["generations"],
            }
            for p, st in sorted(processes.items())
        },
        "n_processes": len(processes),
        "n_generations": n_generations,
        "categories": {k: round(v, 3) for k, v in sorted(categories.items()) if v > 0},
        "goodput_seconds": round(goodput, 3),
        "goodput_frac": round(goodput / wall, 4) if wall > 0 else None,
        "badput_seconds": badput,
        "reassignment_gaps": gaps,
        "top_badput_spans": top,
        "spans": spans_out,
    }


def build_ledger(run_dir) -> Dict[str, Any]:
    """Classified wall-time ledger for a run directory (see module doc).
    Fleet directories additionally fold in lease-reassignment gaps from the
    queue's item lineage."""
    return build_ledger_from_streams(
        load_streams(run_dir),
        run_dir=run_dir,
        reassignment_gaps=fleet_reassignment_gaps(run_dir),
    )


# -- Chrome/Perfetto trace export ---------------------------------------------

# chrome://tracing reserved color names per category (Perfetto accepts and
# ignores unknown cnames, so this degrades gracefully)
_CNAME = {
    "step": "thread_state_running",
    "encode": "thread_state_running",
    "compile": "thread_state_runnable",
    "data_wait": "thread_state_iowait",
    "request_wait": "thread_state_iowait",
    "forward": "thread_state_iowait",
    "dequant": "rail_load",
    "checkpoint": "rail_idle",
    "preempt_drain": "terrible",
    "preempted_down": "terrible",
    "restart_backoff": "bad",
    "degraded_skip": "bad",
    "export_verify": "rail_load",
    "straggler_idle": "thread_state_sleeping",
    "reassign_gap": "black",
    "tower_poll": "rail_load",
    "unaccounted": "grey",
}


def to_chrome_trace(ledger: Dict[str, Any]) -> Dict[str, Any]:
    """Chrome trace-event JSON (the Perfetto-loadable legacy format): one
    ``pid`` per host, one ``tid`` per generation (derived downtime spans ride
    the generation they follow), complete ("X") events in microseconds."""
    spans = ledger.get("spans") or []
    starts = [s["start"] for s in spans if _num(s.get("start")) is not None]
    base = min(starts) if starts else 0.0
    events: List[Dict[str, Any]] = []
    seen_tracks = set()
    for s in spans:
        pid = int(s.get("process_index", 0))
        tid = int(s.get("generation", 0))
        if (pid, "p") not in seen_tracks:
            seen_tracks.add((pid, "p"))
            events.append({
                "ph": "M", "name": "process_name", "pid": pid, "tid": 0,
                "args": {"name": "fleet" if pid < 0 else f"host p{pid}"},
            })
        if (pid, tid) not in seen_tracks:
            seen_tracks.add((pid, tid))
            events.append({
                "ph": "M", "name": "thread_name", "pid": pid, "tid": tid,
                "args": {"name": f"gen {tid}"},
            })
        name = s.get("name") or s["category"]
        args = {"category": s["category"], "seconds": round(s["seconds"], 6)}
        span_traces = [s["trace_id"]] if s.get("trace_id") else list(
            s.get("traces") or ()
        )
        if span_traces:
            args["traces"] = span_traces
        events.append({
            "ph": "X",
            "name": str(name),
            "cat": s["category"],
            "pid": pid,
            "tid": tid,
            "ts": round((s["start"] - base) * 1e6, 1),
            "dur": round(s["seconds"] * 1e6, 1),
            "cname": _CNAME.get(s["category"], "grey"),
            "args": args,
        })
    # per-request track view (ISSUE 14): every trace-tagged span is ALSO
    # emitted on a "requests" process, one thread per trace id, so one
    # request's journey (router forward attempts + the replica batches it
    # rode) reads as one horizontal track in Perfetto
    trace_tids: Dict[str, int] = {}
    request_events: List[Dict[str, Any]] = []
    for s in spans:
        span_traces = [s["trace_id"]] if s.get("trace_id") else list(
            s.get("traces") or ()
        )
        for trace_id in span_traces:
            tid = trace_tids.setdefault(str(trace_id), len(trace_tids))
            name = s.get("name") or s["category"]
            if s.get("replica"):
                name = f"{name}@{s['replica']}"
            request_events.append({
                "ph": "X",
                "name": str(name),
                "cat": s["category"],
                "pid": -2,
                "tid": tid,
                "ts": round((s["start"] - base) * 1e6, 1),
                "dur": round(s["seconds"] * 1e6, 1),
                "cname": _CNAME.get(s["category"], "grey"),
                "args": {"category": s["category"], "trace_id": trace_id,
                         "seconds": round(s["seconds"], 6)},
            })
    if request_events:
        events.append({
            "ph": "M", "name": "process_name", "pid": -2, "tid": 0,
            "args": {"name": "requests (per-trace tracks)"},
        })
        for trace_id, tid in trace_tids.items():
            events.append({
                "ph": "M", "name": "thread_name", "pid": -2, "tid": tid,
                "args": {"name": f"trace {trace_id[:16]}"},
            })
        events.extend(request_events)
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "metadata": {
            "run_dir": ledger.get("run_dir"),
            "goodput_frac": ledger.get("goodput_frac"),
            "trace_base_unix_ts": base,
            "n_traces": len(trace_tids),
        },
    }


# -- rendering ----------------------------------------------------------------

def render_ledger(ledger: Dict[str, Any]) -> str:
    """Markdown-ish ledger summary shared by the timeline CLI and the run
    report's Goodput section."""
    lines: List[str] = []
    wall = ledger["wall_seconds"]
    frac = ledger.get("goodput_frac")
    lines.append(
        f"wall (process-seconds): **{wall:.1f} s** over "
        f"{ledger['n_processes']} process(es), "
        f"{ledger['n_generations']} generation(s)"
    )
    if frac is None:
        lines.append("goodput: n/a (no attributable wall time)")
    elif not ledger.get("has_spans"):
        # a span-less (pre-instrumentation) run: 0 step-seconds is missing
        # data, not a measured 0% — only the derived gap/downtime categories
        # below are real
        lines.append(
            "goodput: n/a (no span instrumentation — only derived "
            "downtime categories are attributed)"
        )
    else:
        lines.append(
            f"goodput: **{100 * frac:.1f}%** "
            f"({ledger['goodput_seconds']:.1f} s productive step compute)"
        )
    badput = ledger.get("badput_seconds") or {}
    if badput:
        lines.append("")
        lines.append("| badput category | seconds | % of wall |")
        lines.append("|---|---:|---:|")
        for cat, secs in sorted(badput.items(), key=lambda kv: -kv[1]):
            pct = 100 * secs / wall if wall > 0 else 0.0
            lines.append(f"| {cat} | {secs:.2f} | {pct:.1f}% |")
    top = ledger.get("top_badput_spans") or []
    if top:
        lines.append("")
        lines.append("Top badput spans:")
        for s in top:
            where = (
                "fleet" if s.get("process_index", 0) < 0
                else f"p{s.get('process_index', 0)} gen {s.get('generation', 0)}"
            )
            lines.append(
                f"- {s['category']} **{s['seconds']:.2f} s** "
                f"({s.get('name') or '-'}, {where})"
            )
    gaps = ledger.get("reassignment_gaps") or []
    if gaps:
        lines.append("")
        lines.append(
            f"Fleet reassignment gaps: {len(gaps)} "
            f"({sum(g['seconds'] for g in gaps):.1f} s total)"
        )
    return "\n".join(lines)
