"""Fleet scheduler: sweep-as-a-service on preemptible worker fleets.

PR 5 made any *single* run survive SIGKILL/SIGTERM with bit-exact resume
(docs/RECOVERY.md); this package (docs/FLEET.md) is the layer above — the
ROADMAP-4 work-queue scheduler that shards a sweep into member-group work
items and drives them across many preemptible workers with at-least-once
execution and exactly-once commits:

  - `queue`     — filesystem work queue: atomic `os.replace` claims, lease
                  files with heartbeat renewal, dead-lease reaping, worker
                  quarantine, per-item reassignment lineage
  - `worker`    — claim → (supervised) train → verify learned-dict export
                  against a size/digest manifest → commit
  - `scheduler` — HBM-watermark-aware member packing, expired-lease
                  reassignment, done-export re-verification
  - `report`    — one fleet dashboard: members done/running/orphaned/lost,
                  per-worker health, the reassignment lineage table

Chaos-tested end to end (`tests/test_fleet.py`): a sharded sweep with
injected worker kills, a torn checkpoint, and transient read errors must
finish with zero lost members, every member bit-exact vs an uninterrupted
run on CPU.
"""

from sparse_coding__tpu.fleet.queue import LeaseLost, WorkQueue, is_fleet_dir
from sparse_coding__tpu.fleet.report import load_fleet, render_fleet_markdown
from sparse_coding__tpu.fleet.scheduler import (
    FleetScheduler,
    build_sweep_items,
    member_bytes_from_run,
    pack_members,
)
from sparse_coding__tpu.fleet.worker import (
    FleetWorker,
    run_item,
    verify_export,
    write_export_manifest,
)

__all__ = [
    "FleetScheduler",
    "FleetWorker",
    "LeaseLost",
    "WorkQueue",
    "build_sweep_items",
    "is_fleet_dir",
    "load_fleet",
    "member_bytes_from_run",
    "pack_members",
    "render_fleet_markdown",
    "run_item",
    "verify_export",
    "write_export_manifest",
]
