"""Streaming PCA / mean baselines.

TPU-native counterpart of the reference `autoencoders/pca.py`. The streaming
covariance update is a jitted pure function over a small state pytree
(`cov, mean, n_samples`) — the thin class wrappers keep the reference's
stateful API for the baseline-runner and eval tooling.

The eigendecomposition happens once per fit (not per batch), so `jnp.eigh` is
fine; the per-batch path is a single rank-b covariance update on the MXU.
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp

from sparse_coding__tpu.models.learned_dict import LearnedDict, Rotation, register_learned_dict
from sparse_coding__tpu.models.topk import TopKLearnedDict, topk_mask_code_static


@jax.jit
def _pca_update(cov, mean, n_samples, activations):
    """Chan et al. streaming covariance/mean update
    (reference `BatchedPCA.train_batch`, `pca.py:53-63`)."""
    batch_size = activations.shape[0]
    total = n_samples + batch_size
    corrected = activations - mean[None, :]
    new_mean = mean + corrected.mean(axis=0) * batch_size / total
    cov_update = jnp.einsum("bi,bj->ij", corrected, activations - new_mean[None, :]) / batch_size
    new_cov = cov * (n_samples / total) + cov_update * batch_size / total
    return new_cov, new_mean, total


class BatchedMean:
    """Streaming mean (reference `BatchedMean`, `pca.py:24-39` — whose
    `train_batch` forgets to increment `n_samples`, reducing it to the mean of
    the *last* batch; we keep the running count, the behavior the code
    intends)."""

    def __init__(self, n_dims: int):
        self.n_dims = n_dims
        self.mean = jnp.zeros((n_dims,))
        self.n_samples = 0.0

    def train_batch(self, activations: jax.Array):
        batch_size = activations.shape[0]
        total = self.n_samples + batch_size
        self.mean = self.mean * (self.n_samples / total) + activations.sum(axis=0) / total
        self.n_samples = total

    def get_mean(self) -> jax.Array:
        return self.mean


class BatchedPCA:
    """Streaming PCA (reference `BatchedPCA`, `pca.py:41-105`)."""

    def __init__(self, n_dims: int):
        self.n_dims = n_dims
        self.cov = jnp.zeros((n_dims, n_dims))
        self.mean = jnp.zeros((n_dims,))
        self.n_samples = jnp.zeros(())

    def get_mean(self) -> jax.Array:
        return self.mean

    def train_batch(self, activations: jax.Array):
        self.cov, self.mean, self.n_samples = _pca_update(
            self.cov, self.mean, self.n_samples, activations
        )

    def get_pca(self) -> Tuple[jax.Array, jax.Array]:
        cov_symm = (self.cov + self.cov.T) / 2
        return jnp.linalg.eigh(cov_symm)

    def get_centering_transform(self) -> Tuple[jax.Array, jax.Array, jax.Array]:
        """(translation, rotation, scaling) whitening triple — feeds
        `FunctionalTiedSAE` centering (reference `pca.py:70-82`)."""
        eigvals, eigvecs = self.get_pca()
        scaling = 1.0 / jnp.sqrt(jnp.clip(eigvals, 1e-6, None))
        return self.get_mean(), eigvecs, scaling

    def get_dict(self) -> jax.Array:
        """Eigvecs as rows, sorted by decreasing eigenvalue (reference `:84-87`)."""
        eigvals, eigvecs = self.get_pca()
        return eigvecs[:, jnp.argsort(-eigvals)].T

    def to_learned_dict(self, sparsity: int) -> "PCAEncoder":
        return PCAEncoder(self.get_dict(), sparsity)

    def to_topk_dict(self, sparsity: int) -> TopKLearnedDict:
        """± components → non-negative top-k dict (reference `:96-100`)."""
        eigvecs = self.get_dict()
        return TopKLearnedDict(jnp.concatenate([eigvecs, -eigvecs], axis=0), sparsity)

    def to_rotation_dict(self, n_components: int) -> Rotation:
        return Rotation(self.get_dict()[:n_components])


def calc_pca(activations: jax.Array, batch_size: int = 512) -> BatchedPCA:
    """Fit streaming PCA over an activation store (reference `pca.py:6-13`)."""
    pca = BatchedPCA(activations.shape[1])
    for i in range(0, activations.shape[0], batch_size):
        pca.train_batch(activations[i : i + batch_size])
    return pca


def calc_mean(activations: jax.Array, batch_size: int = 512) -> jax.Array:
    """Streaming mean of an activation store (reference `pca.py:15-22`)."""
    mean = BatchedMean(activations.shape[1])
    for i in range(0, activations.shape[0], batch_size):
        mean.train_batch(activations[i : i + batch_size])
    return mean.get_mean()


class PCAEncoder(LearnedDict):
    """Top-k-by-|score| PCA projection (reference `PCAEncoder`, `pca.py:108-131`).

    Signed scores are kept for the selected components (unlike the ReLU'd SAE
    codes) — PCA components explain variance in both directions.
    """

    def __init__(self, pca_dict: jax.Array, sparsity: int):
        self.pca_dict = pca_dict / jnp.linalg.norm(pca_dict, axis=-1, keepdims=True)
        self.sparsity = int(sparsity)
        self.n_feats, self.activation_size = self.pca_dict.shape

    def encode(self, x: jax.Array) -> jax.Array:
        scores = jnp.einsum("ij,bj->bi", self.pca_dict, x)
        mask = topk_mask_code_static(jnp.abs(scores), self.sparsity) > 0
        return jnp.where(mask, scores, 0.0)

    def get_learned_dict(self) -> jax.Array:
        return self.pca_dict


register_learned_dict(PCAEncoder, ("pca_dict",), ("sparsity",))
