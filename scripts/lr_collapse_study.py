"""Root-cause study: why lr 1e-3 kills 32k-dim tied-SAE ensembles (VERDICT r2 #3).

Round 2 recorded (dictpar artifact) that Adam lr 1e-3 drives every member of
the 32,768-dim bf16 ensemble to all-zero codes while 3e-4 trains fine — but
did not isolate precision vs optimization or the mechanism. This script runs
the controlled grid on the chip:

    {bf16, fp32 compute} x {lr 1e-3, lr 3e-4}   (config-5 shape, l1 grid)

tracking per-step telemetry that discriminates the candidate mechanisms:
  - mean L0 per member              (the collapse observable)
  - encoder_bias mean               (l1-through-relu pushes biases down;
                                     Adam's normalization makes the push
                                     ~lr/step regardless of gradient size)
  - max pre-activation              (when bias_mean < -max_preact, the relu
                                     gate is shut for every feature = death)
  - reconstruction loss

Writes LR_COLLAPSE_r03.json + a telemetry figure. The companion regression
test (tests/test_lr_guard.py) covers the guard this study motivates:
`train.loop.ensemble_train_loop` warns loudly when every member's L0 hits 0.
"""

from __future__ import annotations

import json
import os
import sys
import time
from pathlib import Path

import numpy as np

REPO = Path(__file__).resolve().parent.parent
ROUND_TAG = os.environ.get("PARITY_ROUND", "r03")

if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))


def main(argv=None):
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true", help="CPU-sized smoke run")
    ap.add_argument("--steps", type=int, default=None)
    ap.add_argument("--out", default=None)
    args = ap.parse_args(argv)

    from sparse_coding__tpu.utils.compile_cache import enable_persistent_compile_cache

    enable_persistent_compile_cache()

    import jax
    import jax.numpy as jnp

    from sparse_coding__tpu.data.synthetic import RandomDatasetGenerator
    from sparse_coding__tpu.ensemble import build_ensemble
    from sparse_coding__tpu.models import FunctionalTiedSAE

    quick = args.quick
    d_act = 64 if quick else 1024
    n_dict = 32 * d_act  # config-5 ratio
    batch = 256 if quick else 2048
    steps = args.steps or (40 if quick else 400)
    probe_every = 4 if quick else 10
    grid = [1e-4, 3e-4, 1e-3, 3e-3]

    gen = RandomDatasetGenerator(
        activation_dim=d_act,
        n_ground_truth_components=2 * d_act,
        batch_size=batch,
        feature_num_nonzero=max(4, d_act // 20),
        feature_prob_decay=0.996,
        correlated=False,
        key=jax.random.PRNGKey(0),
    )
    batches = [next(gen) for _ in range(8)]

    @jax.jit
    def probe(c, params):
        l0 = (c > 0).sum(-1).mean(-1)  # [members]
        bias_mean = params["encoder_bias"].mean(-1)
        return l0, bias_mean

    report = {
        "config": {
            "shape": f"{n_dict}x{d_act}, batch {batch}, steps {steps}",
            "l1_grid": grid,
            "device": jax.devices()[0].device_kind,
        },
        "runs": {},
    }
    for dtype_name, compute_dtype in (("bf16", jnp.bfloat16), ("fp32", None)):
        for lr in (1e-3, 3e-4):
            tag = f"{dtype_name}_lr{lr:g}"
            print(f"== {tag} ==")
            ens = build_ensemble(
                FunctionalTiedSAE,
                jax.random.PRNGKey(1),
                [{"l1_alpha": a} for a in grid],
                optimizer_kwargs={"learning_rate": lr},
                activation_size=d_act,
                n_dict_components=n_dict,
                compute_dtype=compute_dtype,
            )
            tel = {"step": [], "l0": [], "bias_mean": [], "loss": []}
            t0 = time.time()
            for i in range(steps):
                ld, aux = ens.step_batch(batches[i % len(batches)])
                if i % probe_every == 0 or i == steps - 1:
                    l0, bmean = probe(aux["c"], ens.state.params)
                    l0, bmean, loss = jax.device_get((l0, bmean, ld["loss"]))
                    tel["step"].append(i)
                    tel["l0"].append(np.asarray(l0).round(2).tolist())
                    tel["bias_mean"].append(np.asarray(bmean).round(5).tolist())
                    tel["loss"].append(np.asarray(loss).round(6).tolist())
            final_l0 = np.asarray(tel["l0"][-1])
            report["runs"][tag] = {
                "seconds": round(time.time() - t0, 1),
                "final_l0": final_l0.tolist(),
                "collapsed_members": int((final_l0 < 0.5).sum()),
                "telemetry": tel,
            }
            print(
                f"  final L0 {final_l0}  bias_mean {tel['bias_mean'][-1]}  "
                f"({report['runs'][tag]['seconds']}s)"
            )

    # mechanism synthesis: did fp32 collapse too at 1e-3?
    b1, f1 = report["runs"]["bf16_lr0.001"], report["runs"]["fp32_lr0.001"]
    report["conclusion"] = {
        "bf16_lr1e-3_collapsed": b1["collapsed_members"],
        "fp32_lr1e-3_collapsed": f1["collapsed_members"],
        "precision_specific": b1["collapsed_members"] > f1["collapsed_members"],
    }

    out = Path(args.out) if args.out else REPO
    out.mkdir(parents=True, exist_ok=True)
    json_path = out / f"LR_COLLAPSE_{ROUND_TAG}{'_quick' if quick else ''}.json"
    with open(json_path, "w") as f:
        json.dump(report, f, indent=1)
    print(f"Wrote {json_path}")

    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    fig, axes = plt.subplots(1, 3, figsize=(14, 4))
    for tag, run in report["runs"].items():
        tel = run["telemetry"]
        mid = len(grid) // 2  # the lr-grid member closest to the r2 report
        axes[0].plot(tel["step"], [r[mid] for r in tel["l0"]], label=tag)
        axes[1].plot(tel["step"], [r[mid] for r in tel["bias_mean"]], label=tag)
        axes[2].plot(tel["step"], [r[mid] for r in tel["loss"]], label=tag)
    for ax, name in zip(axes, ("mean L0", "encoder bias mean", "loss")):
        ax.set_xlabel("step")
        ax.set_title(name)
        ax.legend(fontsize=7)
    axes[2].set_yscale("log")
    fig.tight_layout()
    fig_path = out / f"lr_collapse_{ROUND_TAG}{'_quick' if quick else ''}.png"
    fig.savefig(fig_path, dpi=110)
    print(f"Wrote {fig_path}")


if __name__ == "__main__":
    main()
