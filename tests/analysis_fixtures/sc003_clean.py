"""Fixture: SC003 clean twin — the same sync, sanctioned both ways the
repo sanctions syncs: an allowed_transfer() block and an allow comment."""

__sclint_hot_entries__ = ("drain", "drain_once")


def drain(outputs, allowed_transfer):
    total = 0.0
    with allowed_transfer():
        for out in outputs:
            total += out.sum().item()
    return total


def drain_once(out):
    return out.sum().item()  # sclint: allow(SC003) end-of-run summary
