"""FISTA: convergence properties + signature smoke tests.

Stronger than the reference's smoke-only `test/fista_test.py:6-41` (which just
checks a tensor comes back): we assert actual sparse-recovery behavior on data
with a known dictionary, per SURVEY.md §4's recommendation to property-test the
pure-math components.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sparse_coding__tpu.ensemble import build_ensemble
from sparse_coding__tpu.models.fista import (
    Fista,
    FunctionalFista,
    dictionary_update,
    fista,
    power_iteration_max_eig,
)


@pytest.fixture(scope="module")
def planted():
    """Known unit-norm dictionary + sparse nonneg codes + clean data."""
    key = jax.random.PRNGKey(0)
    k_dict, k_codes, k_mask = jax.random.split(key, 3)
    n, d, b = 32, 16, 64
    D = jax.random.normal(k_dict, (n, d))
    D = D / jnp.linalg.norm(D, axis=-1, keepdims=True)
    mask = jax.random.bernoulli(k_mask, 0.1, (b, n))
    codes = jax.random.uniform(k_codes, (b, n), minval=0.5, maxval=1.5) * mask
    x = codes @ D
    return D, codes, x


def test_power_iteration_matches_eigvalsh(planted):
    D, _, _ = planted
    lam = power_iteration_max_eig(D, n_iter=50)
    exact = jnp.linalg.eigvalsh(D @ D.T).max()
    assert np.isclose(float(lam), float(exact), rtol=1e-3)


def test_fista_solves_lasso(planted):
    """With small l1, FISTA should nearly reconstruct the planted data."""
    D, codes, x = planted
    ahat, res = fista(x, D, jnp.asarray(1e-4), jnp.zeros_like(codes), num_iter=500)
    # near-perfect reconstruction
    assert float(jnp.mean(res**2)) < 1e-4 * float(jnp.mean(x**2))
    # non-negativity constraint holds
    assert float(ahat.min()) >= 0.0


def test_fista_l1_shrinks_support(planted):
    D, codes, x = planted
    a_lo, _ = fista(x, D, jnp.asarray(1e-4), jnp.zeros_like(codes), num_iter=300)
    a_hi, _ = fista(x, D, jnp.asarray(1e-1), jnp.zeros_like(codes), num_iter=300)
    l0 = lambda a: float((a > 1e-6).sum())
    assert l0(a_hi) < l0(a_lo)


def test_fista_warm_start_converges_faster(planted):
    D, codes, x = planted
    l1 = jnp.asarray(1e-3)
    warm, _ = fista(x, D, l1, jnp.zeros_like(codes), num_iter=200)
    a_cold, res_cold = fista(x, D, l1, jnp.zeros_like(codes), num_iter=20)
    a_warm, res_warm = fista(x, D, l1, warm, num_iter=20)
    assert float(jnp.mean(res_warm**2)) <= float(jnp.mean(res_cold**2)) + 1e-8


def test_dictionary_update_improves_reconstruction(planted):
    """Repeated FISTA basis updates from a perturbed dictionary should reduce
    the residual (dictionary-learning actually learns)."""
    D, codes, x = planted
    key = jax.random.PRNGKey(1)
    D0 = D + 0.3 * jax.random.normal(key, D.shape)
    D0 = D0 / jnp.linalg.norm(D0, axis=-1, keepdims=True)
    hess = jnp.zeros((D.shape[0],))
    l1 = jnp.asarray(1e-3)

    _, res0 = fista(x, D0, l1, jnp.zeros_like(codes), num_iter=300)
    mse0 = float(jnp.mean(res0**2))

    Dk, coeffs = D0, jnp.zeros_like(codes)
    for _ in range(30):
        Dk, hess, res = dictionary_update(Dk, hess, x, coeffs, l1, num_iter=100)
    _, res_final = fista(x, Dk, l1, jnp.zeros_like(codes), num_iter=300)
    mse_final = float(jnp.mean(res_final**2))
    assert mse_final < mse0
    # rows stay unit-norm after updates
    norms = jnp.linalg.norm(Dk, axis=-1)
    assert np.allclose(np.asarray(norms), 1.0, atol=1e-5)


def test_functional_fista_trains_in_ensemble(planted):
    """FunctionalFista members train under the stacked vmap runtime and the
    loss decreases; loss2 (FISTA-in-loss) also steps without error."""
    D, codes, x = planted
    ens = build_ensemble(
        FunctionalFista,
        jax.random.PRNGKey(2),
        [{"l1_alpha": 1e-4}, {"l1_alpha": 1e-3}],
        optimizer_kwargs={"learning_rate": 1e-3},
        activation_size=x.shape[1],
        n_dict_components=D.shape[0],
    )
    first = None
    for _ in range(50):
        loss_dict, _ = ens.step_batch(x)
        if first is None:
            first = jax.device_get(loss_dict["loss"])
    last = jax.device_get(loss_dict["loss"])
    assert (last < first).all()

    # loss2 / fista_loss smoke: finite scalars, gradients exist
    params, buffers = ens.unstack()[0]
    val, (ld, aux) = FunctionalFista.loss2(params, buffers, x, fista_iters=10)
    assert np.isfinite(float(val))
    g = jax.grad(lambda p: FunctionalFista.loss2(p, buffers, x, fista_iters=5)[0])(params)
    assert np.isfinite(float(jnp.abs(g["encoder"]).mean()))
    c0 = jnp.zeros((x.shape[0], D.shape[0]))
    val2, (_, aux2) = FunctionalFista.fista_loss(params, buffers, x, c0, fista_iters=10)
    assert np.isfinite(float(val2))
    assert aux2["c_fista"].shape == c0.shape


def test_fista_learned_dict_export(planted):
    D, _, x = planted
    ld = Fista(D, jnp.zeros((D.shape[0],)))
    c = ld.encode(x)
    assert c.shape == (x.shape[0], D.shape[0])
    assert float(c.min()) >= 0.0
    x_hat = ld.predict(x)
    assert x_hat.shape == x.shape
    a, res = ld.fista(x, jnp.zeros_like(c), jnp.asarray(1e-4), num_iter=200)
    assert float(jnp.mean(res**2)) < float(jnp.mean(x**2))


def test_fista_tol_matches_fixed_iteration_solution(planted):
    """Solve-to-tolerance (tol > 0, the VERDICT-r4-#4 early-exit lever) must
    return the same codes as the blind fixed-500 solve to ~tol, on both the
    XLA path and the Pallas kernel (interpret mode)."""
    from sparse_coding__tpu.ops.fista_pallas import fista_pallas

    D, _, x = planted
    c0 = jnp.zeros((x.shape[0], D.shape[0]))
    l1 = jnp.asarray(1e-3)

    a_fixed, _ = fista(x, D, l1, c0, num_iter=500)
    a_tol, _ = fista(x, D, l1, c0, num_iter=500, tol=1e-4)
    np.testing.assert_allclose(
        np.asarray(a_tol), np.asarray(a_fixed), rtol=0, atol=2e-3
    )
    # support agreement: early exit must not flip active features
    agree = (np.asarray(a_tol) > 0) == (np.asarray(a_fixed) > 0)
    assert agree.mean() > 0.999, agree.mean()

    ap_fixed, _ = fista_pallas(x, D, l1, num_iter=500, interpret=True)
    ap_tol, _ = fista_pallas(x, D, l1, num_iter=500, interpret=True, tol=1e-4)
    np.testing.assert_allclose(
        np.asarray(ap_tol), np.asarray(ap_fixed), rtol=0, atol=2e-3
    )


def test_fista_tol_actually_exits_early():
    """At a realistic dictionary shape the tol=1e-3 solve converges in
    ~100-200 iterations (measured) — observable because the loop is
    iteration-deterministic: if it exits at k iters, every num_iter >= k
    returns identical codes. (The tiny `planted` fixture never crosses the
    threshold — FISTA momentum keeps its max-element delta oscillating — in
    which case tol degrades safely to the fixed-count loop.)"""
    k1, k2, k3 = jax.random.split(jax.random.PRNGKey(1), 3)
    D = jax.random.normal(k1, (1024, 512))
    D = D / jnp.linalg.norm(D, axis=-1, keepdims=True)
    mask = jax.random.bernoulli(k3, 0.01, (128, 1024))
    codes = jax.random.uniform(k2, (128, 1024), minval=0.5, maxval=1.5) * mask
    x = codes @ D + 0.01 * jax.random.normal(k2, (128, 512))
    c0 = jnp.zeros((x.shape[0], D.shape[0]))
    l1 = jnp.asarray(1e-3)
    a_500, _ = fista(x, D, l1, c0, num_iter=500, tol=1e-3)
    a_250, _ = fista(x, D, l1, c0, num_iter=250, tol=1e-3)
    np.testing.assert_array_equal(np.asarray(a_500), np.asarray(a_250))
    # and the converged solve agrees with the blind fixed-500 solution
    a_fixed, _ = fista(x, D, l1, c0, num_iter=500)
    support = (np.asarray(a_500) > 0) == (np.asarray(a_fixed) > 0)
    # ~0.6% of entries flip at the active/inactive boundary (values ~tol)
    assert support.mean() > 0.99, support.mean()
