"""On-disk activation chunk store with double-buffered host→device prefetch.

The framework's only data contract, inherited from the reference: a folder of
numbered chunk files, each an `[N, d_activation]` half-precision array
(reference: torch-saved `{i}.pt`, `activation_dataset.py:393-397`; here:
`{i}.npy` float16 — numpy-native, mmap-able, no torch dependency on the load
path).

TPU-first: the reference loads a chunk into shared host memory and every GPU
worker re-reads it per batch (`cluster_runs.py:101-104`, `big_sweep.py:170`).
Here a chunk is `jax.device_put` once into HBM and batches are on-device
slices; `iter_chunks` overlaps the next chunk's disk read + H2D transfer with
the current chunk's training via a background thread (the double-buffering
called for in SURVEY.md §7 stage 4).
"""

from __future__ import annotations

import functools
import os
import queue
import threading
from pathlib import Path
from typing import Iterator, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np


def chunk_path(folder, i: int) -> Path:
    return Path(folder) / f"{i}.npy"


def scale_path(folder, i: int) -> Path:
    """Per-row dequantization scales of an int8 chunk (absent for fp16)."""
    return Path(folder) / f"{i}.scale.npy"


def quantize_rows_int8(array: np.ndarray):
    """Symmetric per-row absmax int8 quantization: `row ≈ q * scale`.

    Scales stay fp32 ([N], negligible bytes) — their error multiplies every
    element of the row. All-zero rows get scale 1 so dequant is exact."""
    a = np.asarray(array, dtype=np.float32)
    absmax = np.abs(a).max(axis=1)
    scales = np.where(absmax > 0, absmax / 127.0, 1.0).astype(np.float32)
    q = np.clip(np.rint(a / scales[:, None]), -127, 127).astype(np.int8)
    return q, scales


def quantize_rows_int4(array: np.ndarray):
    """Symmetric per-row absmax 4-bit quantization, two values per byte.

    QUARTER the fp16 bytes on disk and over the host→device link (VERDICT r3
    next #5: the tunneled link moves ~20 MiB/s and int8 still starved the
    chip ~14x). Levels are -7..7 (scale = absmax/7), stored offset-by-8 in
    nibbles: byte = ((hi+8)<<4) | (lo+8), so the on-disk dtype is uint8 at
    width d/2 — which is also how `ChunkStore.load` recognizes the format.
    Per-element error ≤ absmax/14: coarse, but SAE-training parity holds
    (tests/test_chunk_quant.py) because the quantization noise is i.i.d.
    and far below the activation signal the dictionary fits.

    Requires even d (every model width in the zoo is)."""
    a = np.asarray(array, dtype=np.float32)
    if a.shape[1] % 2 != 0:
        raise ValueError(f"int4 packing needs an even feature dim, got {a.shape[1]}")
    absmax = np.abs(a).max(axis=1)
    scales = np.where(absmax > 0, absmax / 7.0, 1.0).astype(np.float32)
    q = np.clip(np.rint(a / scales[:, None]), -7, 7).astype(np.int8) + 8
    packed = ((q[:, 0::2].astype(np.uint8) << 4) | q[:, 1::2].astype(np.uint8))
    return packed, scales


def _dequant_int8_impl(q: jax.Array, scales: jax.Array) -> jax.Array:
    return q.astype(jnp.float16) * scales[:, None].astype(jnp.float16)


def _dequant_int4_impl(packed: jax.Array, scales: jax.Array) -> jax.Array:
    hi = (packed >> 4).astype(jnp.int8) - 8
    lo = (packed & 0xF).astype(jnp.int8) - 8
    n, half = packed.shape
    q = jnp.stack([hi, lo], axis=-1).reshape(n, half * 2)
    return q.astype(jnp.float16) * scales[:, None].astype(jnp.float16)


# On-device dequant to fp16 (the store's logical dtype); jitted so the
# widened array never exists host-side.
_dequant_int8 = jax.jit(_dequant_int8_impl)
_dequant_int4 = jax.jit(_dequant_int4_impl)


def _row_sharding(sharding):
    """Sharding for the per-row ``[N]`` scales matching an ``[N, d]`` chunk
    sharding: placed along the chunk's row axis, feature axis dropped.
    NamedSharding only — other kinds return None and the caller leaves the
    scales uncommitted (pre-ADVICE-r3 behavior)."""
    try:
        from jax.sharding import NamedSharding, PartitionSpec

        if isinstance(sharding, NamedSharding):
            row = sharding.spec[0] if len(sharding.spec) else None
            return NamedSharding(sharding.mesh, PartitionSpec(row))
    except (ImportError, TypeError):
        pass
    return None


@functools.lru_cache(maxsize=16)
def _dequant_int8_to(sharding):
    """Dequant jitted with an explicit output sharding, so the result's
    layout is the requested one rather than compiler-chosen (ADVICE r3 —
    fragile on multi-host meshes otherwise). Cached per sharding."""
    return jax.jit(_dequant_int8_impl, out_shardings=sharding)


@functools.lru_cache(maxsize=16)
def _dequant_int4_to(sharding):
    return jax.jit(_dequant_int4_impl, out_shardings=sharding)


def save_chunk(folder, i: int, array, dtype=np.float16) -> Path:
    """Write chunk `i` as `[N, d]` .npy.

    ``dtype=np.float16`` (default): the reference's half-precision contract
    (`activation_dataset.py:393-397`). ``dtype=np.int8``: symmetric per-row
    absmax quantization with an fp32 `{i}.scale.npy` side file — HALF the
    bytes on disk and over the host→device link, dequantized on device by
    `ChunkStore.load`. ``dtype="int4"``: nibble-packed 4-bit tier — QUARTER
    the fp16 bytes (`quantize_rows_int4`). Built for slow links (the
    tunneled bench host moves ~20 MiB/s, VERDICT r2 weak #2 / r3 next #5);
    SAE training on quantize-roundtripped activations is asserted on-par
    with fp16 in tests/test_chunk_quant.py for both tiers."""
    path = chunk_path(folder, i)
    path.parent.mkdir(parents=True, exist_ok=True)
    host = np.asarray(jax.device_get(array))
    if isinstance(dtype, str) and dtype == "int4":
        packed, scales = quantize_rows_int4(host)
        np.save(path, packed)
        np.save(scale_path(folder, i), scales)
    elif np.dtype(dtype) == np.int8:
        q, scales = quantize_rows_int8(host)
        np.save(path, q)
        np.save(scale_path(folder, i), scales)
    else:
        sp = scale_path(folder, i)
        if sp.exists():
            sp.unlink()  # don't let a stale side file reinterpret fp16 bytes
        np.save(path, host.astype(dtype))
    return path


class ChunkStore:
    """A folder of `{i}.npy` activation chunks."""

    def __init__(self, folder):
        self.folder = Path(folder)
        self.folder.mkdir(parents=True, exist_ok=True)

    def __len__(self) -> int:
        # only numbered chunk files — the folder may also hold mean.npy etc.
        return len(
            [p for p in self.folder.iterdir() if p.suffix == ".npy" and p.stem.isdigit()]
        )

    @property
    def n_chunks(self) -> int:
        return len(self)

    def n_datapoints(self) -> int:
        """Total rows across chunks — header-only reads, no data loaded
        (the reference loads every full chunk just to count,
        `big_sweep.py:306-309`)."""
        total = 0
        for i in range(len(self)):
            with open(chunk_path(self.folder, i), "rb") as f:
                version = np.lib.format.read_magic(f)
                shape, _, _ = np.lib.format._read_array_header(f, version)
            total += shape[0]
        return total

    def load(self, i: int, dtype=jnp.float32, device=None, sharding=None) -> jax.Array:
        """Load chunk `i` to device (defaults to JAX's default device).

        The on-disk fp16 bytes are transferred as-is and upcast ON DEVICE:
        host-side upcasting would double the host→device bytes, the dominant
        cost of chunk streaming. ``dtype=None`` keeps the on-disk dtype
        (callers that cache chunks in HBM keep the fp16 footprint and upcast
        per use — exact, fp16→fp32 is lossless).

        int8 chunks (written by ``save_chunk(..., dtype=np.int8)``) move as
        int8 — half the fp16 transfer bytes — and dequantize on device to
        fp16 before any requested upcast; ``dtype=None`` therefore yields
        fp16 for both store formats (the store's logical dtype).

        Transient read errors (network filesystems under pod churn) are
        retried with the shared `utils.sync.retry_with_backoff` schedule
        (`SC_SYNC_RETRIES`/`SC_SYNC_BACKOFF`); each retry bumps the
        telemetry ``io.retry`` counter. The ``chunk_read`` fault site
        (`utils.faults`) lets tests inject the failures deterministically."""
        from sparse_coding__tpu.telemetry.events import counter_inc_active
        from sparse_coding__tpu.utils.faults import fault_point
        from sparse_coding__tpu.utils.sync import retry_with_backoff

        def _read(attempt: int):
            fault_point("chunk_read", chunk=int(i), attempt=attempt)
            a = np.load(chunk_path(self.folder, i))
            sp_ = scale_path(self.folder, i)
            s = (
                np.load(sp_)
                if a.dtype in (np.int8, np.uint8) and sp_.exists()
                else None
            )
            return a, s

        try:
            arr, scales = retry_with_backoff(
                _read,
                retry_on=(OSError,),
                # permanent errors (a chunk index that simply doesn't exist)
                # must fail fast, not burn the backoff schedule
                give_up_on=(
                    FileNotFoundError, IsADirectoryError, NotADirectoryError,
                    PermissionError,
                ),
                on_retry=lambda attempt, exc: counter_inc_active("io.retry"),
            )
        except (
            FileNotFoundError, IsADirectoryError, NotADirectoryError,
            PermissionError,
        ):
            raise
        except OSError:
            # the whole retry schedule burned: count the exhaustion so the
            # report distinguishes "retried and recovered" from "gave up" —
            # drivers turn this into a resumable exit-75 abort
            counter_inc_active("io.exhausted")
            raise
        if scales is not None:
            # int8 = signed bytes; uint8 = nibble-packed int4 (save_chunk's
            # two quantized tiers)
            int4 = arr.dtype == np.uint8
            dequant, dequant_to = (
                (_dequant_int4, _dequant_int4_to) if int4
                else (_dequant_int8, _dequant_int8_to)
            )
            q = jnp.asarray(arr)
            s = jnp.asarray(scales)
            if sharding is not None:
                q = jax.device_put(q, sharding)
                row_sh = _row_sharding(sharding)
                if row_sh is not None:
                    s = jax.device_put(s, row_sh)
                    x = dequant_to(sharding)(q, s)
                else:
                    x = dequant(q, s)
            else:
                if device is not None:
                    q, s = jax.device_put(q, device), jax.device_put(s, device)
                x = dequant(q, s)
        else:
            x = jnp.asarray(arr)
            if sharding is not None:
                x = jax.device_put(x, sharding)
            elif device is not None:
                x = jax.device_put(x, device)
        if dtype is not None and x.dtype != jnp.dtype(dtype):
            x = x.astype(dtype)
        return x

    def iter_chunks(
        self,
        order: Sequence[int],
        dtype=jnp.float32,
        sharding=None,
        center: Optional[jax.Array] = None,
    ) -> Iterator[jax.Array]:
        """Yield chunks in `order`, prefetching the next one on a background
        thread while the caller trains on the current one."""
        q: "queue.Queue" = queue.Queue(maxsize=1)
        stop = threading.Event()

        def producer():
            try:
                for i in order:
                    if stop.is_set():
                        return
                    x = self.load(int(i), dtype=dtype, sharding=sharding)
                    if center is not None:
                        x = x - center[None, :]
                    q.put(("ok", x))
                q.put(("done", None))
            except Exception as e:  # surface loader errors in the consumer
                q.put(("err", e))

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        try:
            while True:
                kind, payload = q.get()
                if kind == "done":
                    return
                if kind == "err":
                    raise payload
                yield payload
        finally:
            stop.set()
            # drain so the producer isn't blocked on put()
            while not q.empty():
                q.get_nowait()


def generate_synthetic_chunks(
    generator,
    folder,
    n_chunks: int,
    chunk_size_gb: float = 2.0,
    activation_width: Optional[int] = None,
    dtype=np.float16,
) -> ChunkStore:
    """Materialize a generator into chunk files
    (reference `generate_synthetic_dataset`, `big_sweep.py:272-281`)."""
    store = ChunkStore(folder)
    width = activation_width or generator.activation_dim
    bytes_per_row = width * np.dtype(dtype).itemsize
    rows_per_chunk = int(chunk_size_gb * 1024**3 // bytes_per_row)
    batches_per_chunk = max(1, rows_per_chunk // generator.batch_size)
    for i in range(n_chunks):
        parts = [np.asarray(jax.device_get(next(generator))) for _ in range(batches_per_chunk)]
        save_chunk(folder, i, np.concatenate(parts, axis=0), dtype=dtype)
    return store
