"""Minimal ensemble-training walkthrough (reference
`ensemble_training_example.py:1-43`, TPU-native form).

Train a 5-member untied-SAE L1 sweep on synthetic sparse data with a planted
dictionary, printing losses and MMCS-to-ground-truth every 100 steps. The
reference broadcasts the batch with `Tensor.expand` and steps one batch per
call; here the batch broadcast is `vmap(in_axes=None)` inside one jitted
step, and 100 steps run per dispatch via `lax.scan` (`step_scan`).

Run: `python examples/ensemble_training_example.py` (any backend).
"""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import jax
import jax.numpy as jnp

from sparse_coding__tpu import build_ensemble
from sparse_coding__tpu.data import RandomDatasetGenerator
from sparse_coding__tpu.metrics import mmcs_to_fixed
from sparse_coding__tpu.models import FunctionalSAE


def main():
    l1_exp_base = 10 ** (1 / 4)
    n_features = 1024
    d_activation = 512
    n_dict_components = 2048
    batch_size = 256

    dataset = RandomDatasetGenerator(
        activation_dim=d_activation,
        n_ground_truth_components=n_features,
        batch_size=batch_size,
        feature_num_nonzero=5,
        feature_prob_decay=0.99,
        correlated=True,
        key=jax.random.PRNGKey(0),
    )

    l1_coefs = [l1_exp_base**i for i in range(-16, -11)]
    ensemble = build_ensemble(
        FunctionalSAE,
        jax.random.PRNGKey(1),
        [{"l1_alpha": l1} for l1 in l1_coefs],
        optimizer_kwargs={"learning_rate": 1e-3},
        activation_size=d_activation,
        n_dict_components=n_dict_components,
    )

    mmcs_all = jax.jit(
        jax.vmap(lambda dec: mmcs_to_fixed(dec / jnp.linalg.norm(dec, axis=-1, keepdims=True), dataset.feats))
    )

    for block in range(10):
        batches = jnp.stack([next(dataset) for _ in range(100)])
        losses = ensemble.step_scan(batches)  # 100 fused steps, one dispatch
        step = (block + 1) * 100
        loss_now = jax.device_get(losses["loss"])[-1]
        mmcss = jax.device_get(mmcs_all(ensemble.state.params["decoder"]))
        print(f"Step {step}")
        print(f"    Losses: {[f'{v:.5f}' for v in loss_now]}")
        print(f"    MMCS: {[f'{v:.3f}' for v in mmcss]}")


if __name__ == "__main__":
    main()
