#!/usr/bin/env bash
# One-shot pre-merge check: static analysis, abstract contracts, generated
# docs, then the tier-1 test suite (ROADMAP.md). Everything a PR must pass,
# in the order that fails fastest.
#
#   scripts/check.sh            # full: sclint + contracts + docs + tier-1
#   scripts/check.sh --fast     # skip the tier-1 pytest run
#
# Exit: nonzero on the first failing stage.

set -u -o pipefail
cd "$(dirname "$0")/.."

fast=0
[ "${1:-}" = "--fast" ] && fast=1

echo "== sclint (static analysis over the shipped tree) =="
JAX_PLATFORMS=cpu python -m sparse_coding__tpu.analysis \
    sparse_coding__tpu/ scripts/ bench.py || exit $?

echo "== sclint contracts (partition coverage, span tables, flags docs) =="
JAX_PLATFORMS=cpu python -m sparse_coding__tpu.analysis --contracts \
    sparse_coding__tpu/analysis || exit $?

echo "== generated docs (utils.flags --check-docs) =="
JAX_PLATFORMS=cpu python -m sparse_coding__tpu.utils.flags --check-docs || exit $?

echo "== tower check (alert gate over the golden tower fixture) =="
JAX_PLATFORMS=cpu python -m sparse_coding__tpu.tower check \
    tests/golden/tower_run || exit $?

echo "== lineage check (taint gate over the golden lineage fixture) =="
JAX_PLATFORMS=cpu python -m sparse_coding__tpu.lineage check \
    tests/golden/lineage_run || exit $?

if [ "$fast" = "1" ]; then
    echo "== tier-1 tests skipped (--fast) =="
    exit 0
fi

echo "== tier-1 tests (ROADMAP.md) =="
rm -f /tmp/_t1.log
timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q \
    -m 'not slow' --continue-on-collection-errors -p no:cacheprovider \
    -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
echo "DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)"
exit "$rc"
