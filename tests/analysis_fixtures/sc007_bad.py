"""Fixture: SC007 violation — an SC_FAULT spec naming a site no
fault_point() in the package declares (the test silently becomes a
control run)."""

import os


def inject():
    os.environ["SC_FAULT"] = "exc:nonexistent_site"  # VIOLATION
