"""Explainer/simulator clients for autointerp.

The reference calls GPT-4 (explain) and text-davinci-003 (simulate) through
`neuron-explainer` with a `secrets.json` OpenAI key read at import time
(`interpret.py:30-32, 334-358`). Here the LLM dependency sits behind a small
protocol so the pipeline is runnable anywhere:

  - `OpenAIClient` — the reference behavior (requires the `openai` package and
    an API key; both absent in this image, so it raises a clear error).
  - `TokenLexiconClient` — deterministic offline fallback: explains a feature
    by its most activation-weighted tokens and simulates by lexicon lookup.
    Not an LLM, but it exercises the full protocol (records → explanation →
    simulation → correlation score) and gives a meaningful baseline score.
"""

from __future__ import annotations

from collections import defaultdict
from typing import Dict, List, Protocol, Sequence

import numpy as np

from sparse_coding__tpu.interp.records import ActivationRecord, calculate_max_activation


class InterpClient(Protocol):
    def explain(self, records: Sequence[ActivationRecord], max_activation: float) -> str: ...

    def simulate(self, explanation: str, tokens: List[str]) -> List[float]: ...


EXPLAINER_MODEL_NAME = "gpt-4"  # reference `interpret.py:50`
SIMULATOR_MODEL_NAME = "text-davinci-003"  # reference `interpret.py:51`


def expected_activation_from_digit_logprobs(top_logprobs: Dict[str, float]) -> float:
    """Calibrated activation from a digit position's top-logprobs.

    The reference scores with `UncalibratedNeuronSimulator` over davinci
    LOGPROBS (`interpret.py:349-358`): rather than trusting the sampled
    digit, take the probability-weighted expectation over the digits 0-10 the
    model considered. Pure function — unit-testable without the API."""
    import math

    ps: Dict[int, float] = {}
    for tok, lp in top_logprobs.items():
        s = tok.strip()
        if s.isdigit() and 0 <= int(s) <= 10:
            # a digit may appear as "5" and " 5"; keep the likelier variant
            ps[int(s)] = max(ps.get(int(s), -math.inf), float(lp))
    if not ps:
        return 0.0
    weights = {k: math.exp(v) for k, v in ps.items()}
    z = sum(weights.values())
    return sum(k * w for k, w in weights.items()) / z


def scores_from_completion_logprobs(
    response_tokens: Sequence[str],
    response_top_logprobs: Sequence[Dict[str, float]],
    n_expected: int,
) -> List[float]:
    """Per-line calibrated activations from a completions response.

    The simulation prompt asks for one `token<TAB>digit` line per input
    token; this walks the response token stream and scores ONLY digit tokens
    whose preceding token is the tab separator — corpus tokens that happen to
    be numeric (dates, counts) are echoed parts of the table's token column
    and must not be read as activation cells, which would shift every later
    score. Missing lines score 0."""
    out: List[float] = []
    prev = "\t"  # the prompt ends with the first row's tab seed
    for tok, top in zip(response_tokens, response_top_logprobs or []):
        if len(out) >= n_expected:
            break
        if tok.strip().isdigit() and prev.endswith("\t"):
            out.append(expected_activation_from_digit_logprobs(top or {tok: 0.0}))
        prev = tok
    out += [0.0] * (n_expected - len(out))
    return out[:n_expected]


class OpenAIClient:
    """LLM explain/simulate via the OpenAI API (reference protocol).

    Explanations use the chat API (gpt-4, reference `interpret.py:334-343`).
    Simulation is CALIBRATED when the simulator is a completions-capable
    model (davinci-style, the reference's `text-davinci-003`): one
    completions call per fragment with `logprobs`, scoring each token by the
    probability-weighted expected digit (`interpret.py:349-358`). Chat-only
    simulator models fall back to parsing printed digits — uncalibrated, as
    no logprobs are available over the digit positions."""

    def __init__(self, api_key: str, explainer_model: str = EXPLAINER_MODEL_NAME,
                 simulator_model: str = SIMULATOR_MODEL_NAME):
        try:
            import openai
        except ImportError as e:
            raise ImportError(
                "the `openai` package is not installed; use TokenLexiconClient "
                "for offline autointerp or install openai"
            ) from e
        self._client = openai.OpenAI(api_key=api_key)
        self.explainer_model = explainer_model
        self.simulator_model = simulator_model

    def _simulator_is_completions_model(self) -> bool:
        name = self.simulator_model
        return "davinci" in name or "babbage" in name or "instruct" in name

    def explain(self, records, max_activation):
        examples = "\n\n".join(
            " ".join(
                f"{tok} ({act:.1f})" if act > 0 else tok
                for tok, act in zip(r.tokens, r.activations)
            )
            for r in records
        )
        resp = self._client.chat.completions.create(
            model=self.explainer_model,
            messages=[
                {
                    "role": "system",
                    "content": (
                        "You explain what pattern a neural-network feature "
                        "responds to, given tokens annotated with activations. "
                        "Reply with a short phrase."
                    ),
                },
                {"role": "user", "content": examples},
            ],
        )
        return resp.choices[0].message.content.strip()

    def simulate(self, explanation, tokens):
        if self._simulator_is_completions_model():
            # all tokens listed up front, prompt ends with "tok0<TAB>" so the
            # model's FIRST sampled token is tok0's activation digit and each
            # continued row follows the demonstrated token<TAB>digit shape
            prompt = (
                f"A neural-network feature activates on: {explanation}\n"
                "Rewrite the token list as a table: one line per token — the "
                "token, a tab, then its activation as an integer 0-10.\n"
                "Tokens: " + " ".join(tokens) + "\n\n"
                f"{tokens[0]}\t"
            )
            resp = self._client.completions.create(
                model=self.simulator_model,
                prompt=prompt,
                max_tokens=4 * len(tokens) + 16,
                temperature=0.0,
                logprobs=5,  # the completions API's maximum
            )
            lp = resp.choices[0].logprobs
            return scores_from_completion_logprobs(
                lp.tokens, lp.top_logprobs, len(tokens)
            )
        # chat fallback: parse printed digits (uncalibrated — chat responses
        # expose no logprobs at the digit positions)
        prompt = (
            f"A feature activates on: {explanation}\n"
            "For each token below, output its activation 0-10, comma-separated.\n"
            + " ".join(tokens)
        )
        resp = self._client.chat.completions.create(
            model=self.simulator_model,
            messages=[{"role": "user", "content": prompt}],
        )
        out = []
        for part in resp.choices[0].message.content.replace("\n", ",").split(","):
            try:
                out.append(float(part.strip()))
            except ValueError:
                out.append(0.0)
        out += [0.0] * (len(tokens) - len(out))
        return out[: len(tokens)]


class TokenLexiconClient:
    """Deterministic offline explainer/simulator.

    Explain: rank tokens by total activation mass across the train records;
    the explanation IS the lexicon (top-k tokens, serialized). Simulate: a
    token's predicted activation is its lexicon weight. A feature that
    genuinely fires on specific tokens scores high; an unexplainable one
    scores ≈ 0 — the same ordering the LLM scorer produces, minus semantics.
    """

    def __init__(self, top_k: int = 10):
        self.top_k = top_k

    def explain(self, records, max_activation):
        import json

        mass: Dict[str, float] = defaultdict(float)
        for r in records:
            for tok, act in zip(r.tokens, r.activations):
                mass[tok] += max(float(act), 0.0)  # numpy scalars break json.dumps
        top = sorted(mass.items(), key=lambda kv: -kv[1])[: self.top_k]
        total = sum(w for _, w in top) or 1.0
        lexicon = {tok: round(w / total, 4) for tok, w in top if w > 0}
        # JSON body: survives tokens containing ',' ':' etc. (real BPE vocabs)
        return "activates on tokens: " + json.dumps(lexicon)

    def simulate(self, explanation, tokens):
        import json

        body = explanation.split("activates on tokens:", 1)[-1].strip()
        try:
            lexicon = json.loads(body)
        except json.JSONDecodeError:
            lexicon = {}
        return [10.0 * float(lexicon.get(tok, 0.0)) for tok in tokens]


def default_client() -> InterpClient:
    """OpenAI if a key is configured (reference reads `secrets.json`,
    `interpret.py:30-32`), else the offline lexicon client."""
    import json
    import os
    from pathlib import Path

    key = os.environ.get("OPENAI_API_KEY")
    if not key and Path("secrets.json").exists():
        key = json.load(open("secrets.json")).get("openai_key")
    if key:
        try:
            return OpenAIClient(key)
        except ImportError:
            pass
    return TokenLexiconClient()
