"""Sparse-autoencoder training signatures (the main model family).

JAX counterparts of the reference `autoencoders/sae_ensemble.py:13-501`. Every
class implements the `DictSignature` protocol (`ensemble.DictSignature`):
pure ``init``/``loss``/``to_learned_dict`` staticmethods over plain pytrees.

Loss conventions match the reference exactly for behavioral parity:
  - reconstruction = mean squared error over *all* elements,
  - l1 = mean over batch of per-example L1 norms of the code,
  - bias_decay = L2 norm of the encoder bias,
  - decoder rows are normalized inside the loss (so the learned dictionary is
    always unit-norm, and gradient flow sees the normalization).

TPU notes: every loss is two MXU matmuls (`bd,dn->bn` and `bn,nd->bd`) plus
fused elementwise ops; under `vmap` over the ensemble axis XLA batches them
into single larger matmuls. Masked variants use multiply-by-mask (not
`masked_fill_`) so the same compiled program serves every dict size.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from sparse_coding__tpu.models.learned_dict import (
    ReverseSAE,
    ThresholdingSAE_export,
    TiedSAE,
    UntiedSAE,
    _norm_rows,
)

_glorot = jax.nn.initializers.glorot_uniform()


def _l1(c: jax.Array) -> jax.Array:
    return jnp.abs(c).sum(axis=-1).mean()


def _safe_l2(x: jax.Array) -> jax.Array:
    """L2 norm with a zero (not NaN) gradient at x == 0, matching the
    subgradient PyTorch uses for `torch.norm` (the biases are zero-initialized,
    so the naive norm would poison the very first step with 0 * NaN)."""
    return jnp.sqrt(jnp.maximum(jnp.sum(x**2), 1e-24))


class FunctionalSAE:
    """Untied SAE: ReLU(Ex + b) → normalized-decoder reconstruction.

    Reference: `autoencoders/sae_ensemble.py:13-77`.
    """

    @staticmethod
    def init(
        key: jax.Array,
        activation_size: int,
        n_dict_components: int,
        l1_alpha: float,
        bias_decay: float = 0.0,
        dtype=jnp.float32,
    ):
        k_enc, k_dec = jax.random.split(key)
        params = {
            "encoder": _glorot(k_enc, (n_dict_components, activation_size), dtype),
            "encoder_bias": jnp.zeros((n_dict_components,), dtype),
            "decoder": _glorot(k_dec, (n_dict_components, activation_size), dtype),
        }
        buffers = {
            "l1_alpha": jnp.asarray(l1_alpha, dtype),
            "bias_decay": jnp.asarray(bias_decay, dtype),
        }
        return params, buffers

    @staticmethod
    def encode(params, buffers, batch):
        c = jnp.einsum("nd,bd->bn", params["encoder"], batch) + params["encoder_bias"]
        return jax.nn.relu(c)

    @staticmethod
    def loss(params, buffers, batch):
        c = FunctionalSAE.encode(params, buffers, batch)
        learned_dict = _norm_rows(params["decoder"])
        x_hat = jnp.einsum("nd,bn->bd", learned_dict, c)
        l_reconstruction = jnp.mean((x_hat - batch) ** 2)
        l_l1 = buffers["l1_alpha"] * _l1(c)
        l_bias_decay = buffers["bias_decay"] * _safe_l2(params["encoder_bias"])
        total = l_reconstruction + l_l1 + l_bias_decay
        loss_data = {
            "loss": total,
            "l_reconstruction": l_reconstruction,
            "l_l1": l_l1,
            "l_bias_decay": l_bias_decay,
        }
        return total, (loss_data, {"c": c})

    @staticmethod
    def to_learned_dict(params, buffers):
        return UntiedSAE(params["encoder"], params["decoder"], params["encoder_bias"])


class FunctionalTiedSAE:
    """Tied SAE (encoder = normalized dictionary) with optional affine
    whitening centering stored in buffers.

    Reference: `autoencoders/sae_ensemble.py:80-160`. The default model for the
    paper sweeps.
    """

    @staticmethod
    def init(
        key: jax.Array,
        activation_size: int,
        n_dict_components: int,
        l1_alpha: float,
        bias_decay: float = 0.0,
        translation: Optional[jax.Array] = None,
        rotation: Optional[jax.Array] = None,
        scaling: Optional[jax.Array] = None,
        dtype=jnp.float32,
    ):
        params = {
            "encoder": _glorot(key, (n_dict_components, activation_size), dtype),
            "encoder_bias": jnp.zeros((n_dict_components,), dtype),
        }
        buffers = {
            "center_rot": rotation if rotation is not None else jnp.eye(activation_size, dtype=dtype),
            "center_trans": translation if translation is not None else jnp.zeros((activation_size,), dtype),
            "center_scale": scaling if scaling is not None else jnp.ones((activation_size,), dtype),
            "l1_alpha": jnp.asarray(l1_alpha, dtype),
            "bias_decay": jnp.asarray(bias_decay, dtype),
        }
        return params, buffers

    @staticmethod
    def center(buffers, batch):
        return (
            jnp.einsum("cu,bu->bc", buffers["center_rot"], batch - buffers["center_trans"][None, :])
            * buffers["center_scale"][None, :]
        )

    @staticmethod
    def uncenter(buffers, batch):
        return (
            jnp.einsum("cu,bc->bu", buffers["center_rot"], batch / buffers["center_scale"][None, :])
            + buffers["center_trans"][None, :]
        )

    @staticmethod
    def encode(params, buffers, batch):
        learned_dict = _norm_rows(params["encoder"])
        batch = FunctionalTiedSAE.center(buffers, batch)
        c = jnp.einsum("nd,bd->bn", learned_dict, batch) + params["encoder_bias"]
        return jax.nn.relu(c)

    @staticmethod
    def loss(params, buffers, batch):
        learned_dict = _norm_rows(params["encoder"])
        batch_centered = FunctionalTiedSAE.center(buffers, batch)
        c = jnp.einsum("nd,bd->bn", learned_dict, batch_centered) + params["encoder_bias"]
        c = jax.nn.relu(c)
        x_hat_centered = jnp.einsum("nd,bn->bd", learned_dict, c)
        l_reconstruction = jnp.mean((x_hat_centered - batch_centered) ** 2)
        l_l1 = buffers["l1_alpha"] * _l1(c)
        l_bias_decay = buffers["bias_decay"] * _safe_l2(params["encoder_bias"])
        total = l_reconstruction + l_l1 + l_bias_decay
        loss_data = {"loss": total, "l_reconstruction": l_reconstruction, "l_l1": l_l1}
        return total, (loss_data, {"c": c})

    @staticmethod
    def to_learned_dict(params, buffers):
        return TiedSAE(
            params["encoder"],
            params["encoder_bias"],
            centering=(buffers["center_trans"], buffers["center_rot"], buffers["center_scale"]),
            norm_encoder=True,
        )


class FunctionalTiedCenteredSAE:
    """Tied SAE with a *learnable* center translation.

    Reference: `autoencoders/sae_ensemble.py:162-228`.
    """

    @staticmethod
    def init(
        key: jax.Array,
        activation_size: int,
        n_dict_components: int,
        l1_alpha: float,
        center: Optional[jax.Array] = None,
        dtype=jnp.float32,
    ):
        params = {
            "center": center if center is not None else jnp.zeros((activation_size,), dtype),
            "encoder": _glorot(key, (n_dict_components, activation_size), dtype),
            "encoder_bias": jnp.zeros((n_dict_components,), dtype),
        }
        buffers = {"l1_alpha": jnp.asarray(l1_alpha, dtype)}
        return params, buffers

    @staticmethod
    def loss(params, buffers, batch):
        learned_dict = _norm_rows(params["encoder"])
        batch_centered = batch - params["center"][None, :]
        c = jnp.einsum("nd,bd->bn", learned_dict, batch_centered) + params["encoder_bias"]
        c = jax.nn.relu(c)
        x_hat_centered = jnp.einsum("nd,bn->bd", learned_dict, c)
        l_reconstruction = jnp.mean((x_hat_centered - batch_centered) ** 2)
        l_l1 = buffers["l1_alpha"] * _l1(c)
        total = l_reconstruction + l_l1
        loss_data = {"loss": total, "l_reconstruction": l_reconstruction, "l_l1": l_l1}
        return total, (loss_data, {"c": c})

    @staticmethod
    def to_learned_dict(params, buffers):
        return TiedSAE(
            params["encoder"],
            params["encoder_bias"],
            centering=(params["center"], None, None),
            norm_encoder=True,
        )


class FunctionalThresholdingSAE:
    """Smooth relu6-based soft-thresholding encoder with learnable
    per-feature scale/gain.

    Reference: `autoencoders/sae_ensemble.py:230-287`. (The reference `encode`
    subtracts a ``params["centering"]`` that its own `init` never creates —
    `sae_ensemble.py:250` — we include it, zero-initialized, so encode works.)
    """

    @staticmethod
    def init(key, activation_size, n_dict_components, l1_alpha, dtype=jnp.float32):
        params = {
            "encoder": _glorot(key, (n_dict_components, activation_size), dtype),
            "activation_scale": jnp.ones((n_dict_components,), dtype),
            "activation_gain": jnp.zeros((n_dict_components,), dtype),
            "centering": jnp.zeros((activation_size,), dtype),
        }
        buffers = {"l1_alpha": jnp.asarray(l1_alpha, dtype)}
        return params, buffers

    @staticmethod
    def encode(params, batch, learned_dict):
        batch = batch - params["centering"][None, :]
        c = jnp.einsum("nd,bd->bn", learned_dict, batch)
        a_sq = params["activation_scale"] ** 2
        c = (c + params["activation_gain"]) / jnp.clip(a_sq, 1e-8, None)
        relu6 = lambda x: jnp.clip(x, 0.0, 6.0)
        c = relu6(60.0 * (c - 0.9)) / 6.0 + jax.nn.relu(c - 1.0)
        return c * a_sq

    @staticmethod
    def loss(params, buffers, batch):
        learned_dict = _norm_rows(params["encoder"])
        c = FunctionalThresholdingSAE.encode(params, batch, learned_dict)
        x_hat = jnp.einsum("nd,bn->bd", learned_dict, c)
        l_reconstruction = jnp.mean((x_hat - batch) ** 2)
        l_l1 = buffers["l1_alpha"] * _l1(c)
        total = l_reconstruction + l_l1
        loss_data = {"loss": total, "l_reconstruction": l_reconstruction, "l_l1": l_l1}
        return total, (loss_data, {"c": c})

    @staticmethod
    def to_learned_dict(params, buffers):
        return ThresholdingSAE_export(params)


class FunctionalMaskedTiedSAE:
    """Tied SAE padded to `n_components_stack` with a coefficient mask, so
    *different dict sizes* can share one vmap stack.

    Reference: `autoencoders/sae_ensemble.py:307-371`. The mask convention
    matches the reference's `coef_mask` (True = masked OUT / unused); we apply
    it as a multiply (`c * keep`) rather than `masked_fill_` — same math,
    XLA-fusable, and vmap-friendly.
    """

    @staticmethod
    def init(
        key,
        activation_size,
        n_dict_components,
        n_components_stack,
        l1_alpha,
        bias_decay: float = 0.0,
        dtype=jnp.float32,
    ):
        params = {
            "encoder": _glorot(key, (n_components_stack, activation_size), dtype),
            "encoder_bias": jnp.zeros((n_components_stack,), dtype),
        }
        keep = (jnp.arange(n_components_stack) < n_dict_components)
        buffers = {
            "l1_alpha": jnp.asarray(l1_alpha, dtype),
            "bias_decay": jnp.asarray(bias_decay, dtype),
            "dict_size": jnp.asarray(n_dict_components, jnp.int32),
            "coef_keep": keep.astype(dtype),
        }
        return params, buffers

    @staticmethod
    def loss(params, buffers, batch):
        learned_dict = _norm_rows(params["encoder"])
        c = jnp.einsum("nd,bd->bn", learned_dict, batch) + params["encoder_bias"]
        c = jax.nn.relu(c) * buffers["coef_keep"][None, :]
        x_hat = jnp.einsum("nd,bn->bd", learned_dict, c)
        l_reconstruction = jnp.mean((x_hat - batch) ** 2)
        l_l1 = buffers["l1_alpha"] * _l1(c)
        total = l_reconstruction + l_l1
        loss_data = {"loss": total, "l_reconstruction": l_reconstruction, "l_l1": l_l1}
        return total, (loss_data, {"c": c})

    @staticmethod
    def to_learned_dict(params, buffers):
        n = int(buffers["dict_size"])
        return TiedSAE(params["encoder"][:n], params["encoder_bias"][:n], norm_encoder=True)


class FunctionalMaskedSAE:
    """Untied masked SAE (different dict sizes in one stack).

    Reference: `autoencoders/sae_ensemble.py:375-442`.
    """

    @staticmethod
    def init(
        key,
        activation_size,
        n_dict_components,
        n_components_stack,
        l1_alpha,
        bias_decay: float = 0.0,
        dtype=jnp.float32,
    ):
        k_enc, k_dec = jax.random.split(key)
        params = {
            "encoder": _glorot(k_enc, (n_components_stack, activation_size), dtype),
            "encoder_bias": jnp.zeros((n_components_stack,), dtype),
            "decoder": _glorot(k_dec, (n_components_stack, activation_size), dtype),
        }
        keep = (jnp.arange(n_components_stack) < n_dict_components)
        buffers = {
            "l1_alpha": jnp.asarray(l1_alpha, dtype),
            "bias_decay": jnp.asarray(bias_decay, dtype),
            "dict_size": jnp.asarray(n_dict_components, jnp.int32),
            "coef_keep": keep.astype(dtype),
        }
        return params, buffers

    @staticmethod
    def loss(params, buffers, batch):
        learned_dict = _norm_rows(params["decoder"])
        c = jnp.einsum("nd,bd->bn", params["encoder"], batch) + params["encoder_bias"]
        c = jax.nn.relu(c) * buffers["coef_keep"][None, :]
        x_hat = jnp.einsum("nd,bn->bd", learned_dict, c)
        l_reconstruction = jnp.mean((x_hat - batch) ** 2)
        l_l1 = buffers["l1_alpha"] * _l1(c)
        total = l_reconstruction + l_l1
        loss_data = {"loss": total, "l_reconstruction": l_reconstruction, "l_l1": l_l1}
        return total, (loss_data, {"c": c})

    @staticmethod
    def to_learned_dict(params, buffers):
        n = int(buffers["dict_size"])
        return UntiedSAE(params["encoder"][:n], params["decoder"][:n], params["encoder_bias"][:n])


class FunctionalReverseSAE:
    """Tied SAE that subtracts the bias again for active features pre-decode.

    Reference: `autoencoders/sae_ensemble.py:445-501`. The boolean-indexed
    in-place update of the reference (`:481-482`) becomes a `jnp.where` — same
    values, trace-safe.
    """

    @staticmethod
    def init(key, activation_size, n_dict_components, l1_alpha, bias_decay=0.0, dtype=jnp.float32):
        params = {
            "encoder": _glorot(key, (n_dict_components, activation_size), dtype),
            "encoder_bias": jnp.zeros((n_dict_components,), dtype),
        }
        buffers = {
            "l1_alpha": jnp.asarray(l1_alpha, dtype),
            "bias_decay": jnp.asarray(bias_decay, dtype),
        }
        return params, buffers

    @staticmethod
    def loss(params, buffers, batch):
        learned_dict = _norm_rows(params["encoder"])
        c = jnp.einsum("nd,bd->bn", learned_dict, batch) + params["encoder_bias"]
        c = jax.nn.relu(c)
        c = jnp.where(c > 0.0, c - params["encoder_bias"][None, :], c)
        x_hat = jnp.einsum("nd,bn->bd", learned_dict, c)
        l_reconstruction = jnp.mean((x_hat - batch) ** 2)
        l_l1 = buffers["l1_alpha"] * _l1(c)
        l_bias_decay = buffers["bias_decay"] * _safe_l2(params["encoder_bias"])
        total = l_reconstruction + l_l1 + l_bias_decay
        loss_data = {
            "loss": total,
            "l_reconstruction": l_reconstruction,
            "l_l1": l_l1,
            "l_bias_decay": l_bias_decay,
        }
        return total, (loss_data, {"c": c})

    @staticmethod
    def to_learned_dict(params, buffers):
        return ReverseSAE(params["encoder"], params["encoder_bias"], norm_encoder=True)
