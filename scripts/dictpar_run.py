"""BASELINE config 5 artifact: 32x-overcomplete dictionary sweep with dict-axis
tensor parallelism (Pythia-410M geometry).

The reference's largest workload family is a >=32x overcomplete dictionary on a
mid-size LM (`big_sweep_experiments.py:546-644` dict_ratio grids up to 32,
BASELINE.json config 5: "Pythia-410M residual mid-layer, 32x over-complete
dict, multi-host v4-32 pod sweep"). This script produces the two halves of
that story this environment can measure:

1. **Real-chip run** (default): harvest ~10.5M rows of Pythia-410M-geometry
   mid-layer residual activations (trigram-pretrained subject), quantize
   them ON DEVICE to the int8 chunk tier so they stay HBM-resident
   (10.7 GB instead of 21 GB bf16 — `data.chunks`; training parity vs fp16
   is test-asserted), and train 4-member l1 ensembles of tied SAEs at dict
   ratio 32 (n_dict=32768, d=1024) to an FVU plateau (trajectory recorded),
   with FVU/L0 pareto, dead features counted over a 65k-row held-out
   sample, cross-seed MMCS vs the random-direction floor, and
   perplexity-under-reconstruction. Activations are standardized by a
   scalar std folded into the dequant scales; lr 3e-4 — measured on the
   chip: lr 1e-3 collapses the 32768-dim ensemble's high-l1 members to
   zero codes (NOT a bf16 effect: the round-3 LR_COLLAPSE study's fp32
   control collapses identically — it is the l1-pressure x Adam-lr
   dynamic). At this shape the fused-kernel VMEM gate
   (`ops.tied_sae_kernel.fused_fits`) correctly routes training to the XLA
   path — exercised and asserted here. (Round 3's two-depth layer-2-vs-mid
   comparison stands in PARITY_r03_dictpar.json.)

2. **Pod-sharding validation** (subprocess on a virtual 8-device CPU mesh,
   because multi-chip hardware is not reachable from this environment —
   the real v4-32 run differs only in `jax.distributed.initialize`, see
   `parallel/distributed.py`): the SAME ensemble shape sharded over a
   (model=2, data=2, dict=2) mesh, stepped, asserted numerically identical
   to the unsharded step, with the dictionary + Adam moments confirmed
   dict-axis-sharded (per-device parameter bytes halve).

Writes PARITY_<round>_dictpar.json (+ pareto figure) at the repo root.
Run: `python scripts/dictpar_run.py` (real chip, ~5 min). `--quick` is a
CPU-sized smoke mode used by the test suite.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time
from pathlib import Path

import numpy as np

REPO = Path(__file__).resolve().parent.parent
ROUND_TAG = os.environ.get("PARITY_ROUND", "r05")  # artifact round tag


if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

RATIO = 32


def subject_geometry(quick: bool):
    """(d_model, n_layers, n_heads, d_mlp, layer) — pythia-410m geometry
    (EleutherAI config: d=1024, 24 layers, 16 heads) with its mid layer."""
    if quick:
        return 64, 3, 4, 128, 1
    return 1024, 24, 16, 4096, 12


def build_subject_model(quick: bool, checkpoint: str = None):
    """Thin wrapper over `parity_run.build_subject_model` with the
    pythia-410m geometry (the scripts share one subject builder).
    ``checkpoint`` loads real weights instead (real_subject_run path)."""
    from parity_run import build_subject_model as build

    if checkpoint:
        return build(quick, checkpoint=checkpoint)
    d, L, h, mlp, _ = subject_geometry(quick)
    return build(
        quick, "neox",
        hf_kwargs=dict(
            vocab_size=50304, hidden_size=d, num_hidden_layers=L,
            num_attention_heads=h, intermediate_size=mlp,
            max_position_embeddings=2048,
        ),
    )


def mesh_validate(quick: bool) -> dict:
    """Run in a subprocess with a virtual 8-device CPU mesh: shard the
    config-5 ensemble over (model=2, data=2, dict=2), assert step parity with
    the unsharded ensemble and dict-axis sharding of params + Adam moments."""
    import jax
    import jax.numpy as jnp

    from sparse_coding__tpu import build_ensemble
    from sparse_coding__tpu.models import FunctionalTiedSAE
    from sparse_coding__tpu.parallel import make_mesh

    d_act, *_ = subject_geometry(quick)
    n_dict = RATIO * d_act
    batch = 128 if quick else 512
    n_steps = 2

    def build():
        return build_ensemble(
            FunctionalTiedSAE,
            jax.random.PRNGKey(0),
            [{"l1_alpha": a} for a in (1e-4, 3e-4, 1e-3, 3e-3)],
            optimizer_kwargs={"learning_rate": 1e-3},
            activation_size=d_act,
            n_dict_components=n_dict,
        )

    batches = [
        jax.random.normal(jax.random.PRNGKey(10 + i), (batch, d_act))
        for i in range(n_steps)
    ]

    ref = build()
    for b in batches:
        ref_loss, _ = ref.step_batch(b)

    mesh = make_mesh(2, 2, 2)
    sharded = build().shard(mesh)
    enc = sharded.state.params["encoder"]
    mu_enc = sharded.state.opt_state[0].mu["encoder"]
    enc_spec = str(enc.sharding.spec)
    mu_spec = str(mu_enc.sharding.spec)
    per_device_bytes = enc.addressable_shards[0].data.nbytes
    assert "dict" in enc_spec and "model" in enc_spec, enc_spec
    assert mu_spec == enc_spec, (mu_spec, enc_spec)
    # model axis 2 x dict axis 2 => each device holds a quarter of the stack
    assert per_device_bytes * 4 == enc.nbytes, (per_device_bytes, enc.nbytes)

    for b in batches:
        sh_loss, _ = sharded.step_batch(b)

    a = np.asarray(jax.device_get(ref_loss["loss"]))
    b_ = np.asarray(jax.device_get(sh_loss["loss"]))
    rel = float(np.abs(a - b_).max() / (np.abs(a).max() + 1e-12))
    assert rel < 1e-4, rel
    assert np.isfinite(b_).all()

    return {
        "mesh": "model=2 x data=2 x dict=2 (8 virtual CPU devices)",
        "n_dict": n_dict,
        "d_act": d_act,
        "encoder_spec": enc_spec,
        "adam_mu_spec": mu_spec,
        "encoder_bytes_total": int(enc.nbytes),
        "encoder_bytes_per_device": int(per_device_bytes),
        "steps": n_steps,
        "loss_rel_diff_vs_unsharded": rel,
        "hardware_note": (
            "multi-chip hardware is not reachable from this environment; the "
            "v4-32 pod run differs only by jax.distributed.initialize "
            "(parallel/distributed.py) — the sharded program is identical"
        ),
    }


def main(argv=None):
    from sparse_coding__tpu.utils.compile_cache import enable_persistent_compile_cache

    enable_persistent_compile_cache()
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--quick", action="store_true", help="CPU-sized smoke run")
    ap.add_argument("--out", default=None, help="output prefix (default repo root)")
    ap.add_argument("--mesh-validate", action="store_true", help=argparse.SUPPRESS)
    ap.add_argument(
        "--pretrain", type=int, default=-1,
        help="subject pretraining steps on the synthetic trigram corpus "
        "(-1 = auto: 2000 for full runs, 0 for --quick; 0 = random-init "
        "subject)",
    )
    ap.add_argument(
        "--max-epochs", type=int, default=None,
        help="override the plateau-training epoch cap",
    )
    ap.add_argument(
        "--l1-warmup-steps", type=int, default=0,
        help="ramp l1_alpha from ~0 over this many steps in every ensemble "
        "(ensemble.make_ensemble_step) — the anti-collapse lever for the "
        "32x dict's low-l1 dead-fraction (VERDICT r4 next #2; proven at "
        "this shape in RESURRECT_r04_warmup*.json)",
    )
    ap.add_argument(
        "--subject", default=None,
        help="REAL subject weights: HF model name or local save_pretrained "
        "dir via lm.convert.load_model (disables trigram pretraining). "
        "Driven by scripts/real_subject_run.py",
    )
    ap.add_argument(
        "--tokens-file", default=None,
        help=".npy [rows, >=seq_len] pre-tokenized harvest text "
        "(pairs with --subject)",
    )
    args = ap.parse_args(argv)
    if args.max_epochs is not None and args.max_epochs < 1:
        ap.error("--max-epochs must be >= 1")

    if args.mesh_validate:
        # child mode: force the virtual CPU mesh BEFORE jax backend init
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8"
        ).strip()
        import jax

        jax.config.update("jax_platforms", "cpu")
        print("MESH_VALIDATE_JSON=" + json.dumps(mesh_validate(args.quick)))
        return None

    import jax
    import jax.numpy as jnp

    from sparse_coding__tpu import build_ensemble, metrics as sm
    from sparse_coding__tpu.data.activations import harvest_to_device
    from sparse_coding__tpu.models import FunctionalTiedSAE
    from sparse_coding__tpu.models.learned_dict import Identity
    from sparse_coding__tpu.train.loop import ensemble_train_loop

    t_start = time.time()
    quick = args.quick
    d_act, n_layers, _, _, layer = subject_geometry(quick)
    n_dict = RATIO * d_act
    seq_len = 32 if quick else 256
    batch_rows = 16 if quick else 64
    # r4 scale (VERDICT r3 next #1): 40 x 0.5 GB chunks = ~10.5M unique rows,
    # held HBM-resident as int8 (per-row absmax, the data.chunks tier — 10.7
    # GB instead of 21 GB bf16; training parity vs fp16 is asserted in
    # tests/test_chunk_quant.py) and dequantized per chunk at train time.
    chunk_gb = 0.002 if quick else 0.5
    sae_batch = 256 if quick else 2048
    n_chunks = 2 if quick else 40
    max_epochs = 1 if quick else 8
    if args.max_epochs is not None:
        max_epochs = args.max_epochs
    plateau_tol = 0.003
    grid = [1e-4, 1e-3] if quick else [1e-4, 3e-4, 1e-3, 3e-3]
    seeds = (0, 1)
    eval_rows = 2048 if quick else 8192
    dead_eval_rows = 2048 if quick else 65536

    print("Building subject model "
          + (f"(REAL weights: {args.subject})..." if args.subject
             else f"(pythia-410m geometry, d={d_act})..."))
    lm_cfg, params = build_subject_model(quick, checkpoint=args.subject)

    from parity_run import (
        SUBJECT_CAVEAT,
        corpus_tokens,
        file_tokens,
        maybe_pretrain,
        real_subject_caveat,
        tiling_caveat,
    )

    pretrain_steps = args.pretrain if args.pretrain >= 0 else (0 if quick else 2000)
    if args.subject:
        pretrain_steps = 0  # real weights
        # geometry follows the loaded checkpoint, mid layer by the spec
        # (cap_layers is derived from `layer` below, after this override)
        d_act, n_layers = lm_cfg.d_model, lm_cfg.n_layers
        layer = n_layers // 2
        n_dict = RATIO * d_act
    params, lang, pretrain_stats = maybe_pretrain(
        params, lm_cfg, quick, pretrain_steps
    )
    # seed=0 keeps the --pretrain 0 path token-identical to the round-2 runs
    tiling_info = None
    if args.tokens_file:
        tokens, tiling_info = file_tokens(
            args.tokens_file, lm_cfg.vocab_size, d_act, chunk_gb, batch_rows,
            seq_len, n_chunks + 1,
        )
    else:
        tokens = corpus_tokens(
            lang, lm_cfg.vocab_size, d_act, chunk_gb, batch_rows, seq_len,
            n_chunks + 1, seed=0 if lang is None else 13,
        )
    n_rows = tokens.shape[0]

    # r3 captured layer 2 + the mid layer in one pass (that two-depth
    # evidence stands in PARITY_r03_dictpar.json); r4 spends the whole HBM
    # budget on the spec's mid layer at 10.5M rows instead.
    cap_layers = [layer]
    # 1e-3 collapses the 32768-dim ensemble's high-l1 members (all-zero
    # codes). LR_COLLAPSE_r03.json: fp32 control collapses identically, so
    # this is the l1-pressure x Adam-lr dynamic, not precision; the train
    # loop's dead-ensemble watchdog (train.loop.warn_if_ensemble_dead) now
    # catches it loudly.
    lr = 3e-4
    report: dict = {
        "config": {
            "baseline_config": 5,
            "subject": f"{lm_cfg.arch} d={d_act} L={n_layers} "
            + (f"(REAL weights: {args.subject})" if args.subject else
               f"(pythia-410m geometry, "
               f"{'trigram-pretrained' if lang is not None else 'random init'})"),
            "model": "FunctionalTiedSAE",
            "layers": cap_layers, "mid_layer": layer, "layer_loc": "residual",
            "seq_len": seq_len, "dict_ratio": RATIO, "n_dict": n_dict,
            "l1_alpha_grid": grid, "sae_batch": sae_batch,
            "max_epochs": max_epochs, "plateau_tol": plateau_tol,
            "seeds": list(seeds),
            "l1_warmup_steps": args.l1_warmup_steps,
            "device": jax.devices()[0].device_kind,
        },
        "subject_caveat": tiling_caveat(
            real_subject_caveat(args) if args.subject else SUBJECT_CAVEAT,
            tiling_info,
        ),
        **({"harvest_tiling": tiling_info} if tiling_info else {}),
        **({"pretrain": pretrain_stats} if pretrain_stats else {}),
        "notes": (
            f"{'trigram-pretrained' if lang is not None else 'random-init'} "
            "subject; activations standardized by a scalar std folded into "
            "the int8 dequant scales (recorded below). lr 3e-4: lr 1e-3 "
            "kills the high-l1 members (LR_COLLAPSE_r03: fp32 collapses "
            "identically - l1 x Adam-lr dynamics, not bf16). Train chunks "
            "are held HBM-resident int8 (data.chunks tier; training parity "
            "vs fp16 asserted in tests/test_chunk_quant.py) so ~10.5M "
            "unique rows fit one v5e."
        ),
    }

    print(f"Harvesting {n_chunks + 1} chunks ({n_rows * seq_len:,} tokens, fused)...")
    t0 = time.time()
    # fused harvest→HBM (data.activations.harvest_to_device: the disk path
    # is ~95% device→host transfer on this backend, THROUGHPUT.md r2f).
    # Each train chunk is int8-quantized ON DEVICE as it arrives; the scalar
    # standardization (first chunk's std) is folded into the stored dequant
    # scales, so train-time dequant yields standardized bf16 in one jit.
    @jax.jit
    def _quant8(x, inv_std):
        xf = x.astype(jnp.float32) * inv_std
        absmax = jnp.abs(xf).max(axis=1)
        s = jnp.where(absmax > 0, absmax / 127.0, 1.0)
        q = jnp.clip(jnp.rint(xf / s[:, None]), -127, 127).astype(jnp.int8)
        return q, s

    @jax.jit
    def _dequant8(q, s):
        return (q.astype(jnp.float32) * s[:, None]).astype(jnp.bfloat16)

    L = layer
    q_chunks = []
    act_std = inv_std = eval_chunk = dead_eval = None
    for i, chunk in enumerate(harvest_to_device(
        params, lm_cfg, tokens, cap_layers, ["residual"],
        batch_size=batch_rows, chunk_size_gb=chunk_gb, n_chunks=n_chunks + 1,
    )):
        arr = chunk[(L, "residual")]
        if act_std is None:
            act_std = float(arr.astype(jnp.float32).std())
            inv_std = jnp.asarray(1.0 / act_std, jnp.float32)
        if i < n_chunks:
            q_chunks.append(_quant8(arr, inv_std))
        else:
            full = arr.astype(jnp.float32) * inv_std
            dead_eval = full[:dead_eval_rows]
            eval_chunk = full[:eval_rows]
            del full
        del arr
    jax.device_get(eval_chunk[0, 0])  # fence for honest timing
    harvest_s = time.time() - t0
    report[f"activation_std_l{L}"] = act_std
    n_train_rows = sum(int(q.shape[0]) for q, _ in q_chunks)
    report["harvest"] = {
        "seconds": round(harvest_s, 1),
        "tokens_per_sec": round(n_rows * seq_len / harvest_s, 1),
        "train_rows": int(n_train_rows),
        "path": "harvest_to_device -> on-device int8 (HBM-resident)",
        "capture_points": [f"layer {L} residual" for L in cap_layers],
    }
    print(f"  {harvest_s:.0f}s ({report['harvest']['tokens_per_sec']:.0f} tok/s, "
          f"{n_train_rows:,} train rows int8-resident)")

    # free the subject LM for the training phase (~1.6 GB HBM at 410m
    # geometry); it returns for the perplexity eval via one host round trip
    params_host = jax.device_get(params)
    params = None

    dicts_store = {}
    pareto = {}
    total_rows_consumed = 0
    eval_s = train_wall = 0.0
    t_all = time.time()
    for seed in seeds:
        ens = build_ensemble(
            FunctionalTiedSAE, jax.random.PRNGKey(seed),
            [{"l1_alpha": float(a)} for a in grid],
            optimizer_kwargs={"learning_rate": lr},
            compute_dtype=None if quick else jnp.bfloat16,
            activation_size=d_act, n_dict_components=n_dict,
            l1_warmup_steps=args.l1_warmup_steps,
        )
        # the VMEM gate must refuse the fused kernel at 32x overcomplete
        # and route to the XLA path (the whole point of the gate)
        assert not ens.fused, "fused kernel must not engage at 32x dict"
        key = jax.random.PRNGKey(100 + seed)
        losses_first = losses_last = None
        traj = []
        prev = None
        stall = diverge = 0
        consumed = 0
        t_train = 0.0
        for epoch in range(max_epochs):
            te = time.time()
            for q, s in q_chunks:
                key, k = jax.random.split(key)
                chunk = _dequant8(q, s)
                losses = ensemble_train_loop(ens, chunk, batch_size=sae_batch, key=k)
                del chunk
                if losses_first is None:
                    losses_first = np.asarray(jax.device_get(losses["loss"]))
            losses_last = np.asarray(jax.device_get(losses["loss"]))  # fence
            t_train += time.time() - te
            consumed += n_train_rows
            fvus = [
                float(r["fvu"])
                for r in sm.evaluate_dicts(ens.to_learned_dicts(), eval_chunk)
            ]
            cur = float(np.mean(fvus))
            traj.append({"epoch": epoch, "mean_fvu": round(cur, 5),
                         "fvu": [round(f, 5) for f in fvus]})
            print(f"  seed {seed} epoch {epoch}: mean FVU {cur:.4f}")
            if prev is not None:
                delta = prev - cur  # positive = improvement
                if delta < -plateau_tol * prev:
                    diverge += 1
                    stall = 0
                elif delta < plateau_tol * prev:
                    stall += 1
                    diverge = 0
                else:
                    stall = diverge = 0
            prev = cur
            if stall >= 2 or diverge >= 2:
                break
        train_wall += t_train
        total_rows_consumed += consumed
        report[f"train_l{L}_s{seed}"] = {
            "loss_first_chunk": [float(x) for x in losses_first],
            "loss_last_chunk": [float(x) for x in losses_last],
            "epochs_run": len(traj),
            "plateau_reached": bool(stall >= 2),
            "diverged": bool(diverge >= 2),
            "rows_consumed": int(consumed),
            "train_seconds": round(t_train, 1),
            "sustained_rows_per_sec": (
                round(consumed / t_train, 1) if t_train > 0 else None
            ),
            "fvu_trajectory": traj,
        }
        dicts = ens.to_learned_dicts()
        del ens  # free mu/nu (1.6 GB) before the next build
        dicts_store[(L, seed)] = dicts
        t0 = time.time()
        rows = sm.evaluate_dicts(dicts, eval_chunk)
        # dead-feature counting over a larger held-out sample: at 32k dicts
        # the >10-activation threshold on a small eval set undercounts the
        # live set (VERDICT r3 weak #2)
        dead = [
            int(ld.n_feats)
            - sm.batched_calc_feature_n_ever_active(ld, dead_eval, threshold=10)
            for ld in dicts
        ]
        eval_s += time.time() - t0
        pareto[f"layer{L}_seed{seed}"] = [
            {
                "l1_alpha": float(a), "fvu": row["fvu"], "l0": row["l0"],
                "r2": row["r2"], "n_dead": int(d), "n_feats": int(ld.n_feats),
                "dead_eval_rows": int(dead_eval.shape[0]),
            }
            for a, row, d, ld in zip(grid, rows, dead, dicts)
        ]
    report["train_seconds"] = round(time.time() - t_all, 1)
    report["rows_consumed_total"] = int(total_rows_consumed)
    report["sustained_acts_per_sec_all_ensembles"] = (
        round(total_rows_consumed / train_wall, 1) if train_wall else None
    )
    report["pareto"] = pareto
    print(f"Trained {len(seeds)} ensembles in {report['train_seconds']}s "
          f"({total_rows_consumed:,} rows consumed)")
    # the 10.7 GB int8 residency ends here: the MMCS einsums below
    # materialize 32768x32768 fp32 (~4.3 GB) transients and the subject LM
    # comes back for perplexity — all three never coexist with the chunks
    del q_chunks

    report["mmcs_cross_seed"] = {
        f"layer{L}": {
            f"{a:.2e}": float(sm.mmcs(x, y))
            for a, x, y in zip(
                grid, dicts_store[(L, seeds[0])], dicts_store[(L, seeds[1])]
            )
        }
        for L in cap_layers
    }
    # the null every trained cross-seed MMCS must clear (VERDICT r3 next #6)
    from parity_run import mmcs_random_floor

    report["mmcs_random_floor"] = mmcs_random_floor(n_dict, d_act)
    d0 = dicts_store[(layer, seeds[0])]

    # perplexity under reconstruction: the subject LM returns to HBM now
    # that the int8 chunks are freed (the two never coexist — peak residency
    # is the binding constraint of this script)
    params = jax.tree.map(jnp.asarray, params_host)
    del params_host
    eval_tokens = jnp.asarray(tokens[: (4 if quick else 8)])
    mid = len(grid) // 2
    # fold the training standardization into the dict's centering hooks so
    # the reconstruction hook sees raw activations: center(x) = x/std,
    # uncenter multiplies back (TiedSAE affine centering, scale-only)
    mid_ld = d0[mid]
    inv_std = jnp.full((d_act,), 1.0 / report[f"activation_std_l{layer}"])
    scaled_mid = type(mid_ld)(
        mid_ld.encoder, mid_ld.encoder_bias,
        centering=(None, None, inv_std), norm_encoder=mid_ld.norm_encoder,
    )
    ppl_dicts = [
        (scaled_mid, {"l1_alpha": grid[mid], "standardized": True}),
        (Identity(d_act), {"baseline": "identity"}),
    ]
    t0 = time.time()
    base_loss, ppl = sm.calculate_perplexity(
        params, lm_cfg, ppl_dicts, (layer, "residual"), eval_tokens,
        batch_size=4,
    )
    report["perplexity"] = {
        "base_lm_loss": float(base_loss),
        "under_reconstruction": [
            {**hp, "lm_loss": float(loss)} for hp, loss in ppl
        ],
    }
    report["eval_seconds"] = round(eval_s + time.time() - t0, 1)

    # pod-sharding half: subprocess so the virtual CPU mesh can't disturb
    # this process's TPU backend
    print("Validating dict-parallel sharding on the virtual 8-device mesh...")
    t0 = time.time()
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)  # child pins cpu via jax.config
    proc = subprocess.run(
        [sys.executable, str(Path(__file__).resolve()), "--mesh-validate"]
        + (["--quick"] if quick else []),
        capture_output=True, text=True, env=env, timeout=1200,
    )
    if proc.returncode != 0:
        raise RuntimeError(f"mesh validation failed:\n{proc.stdout}\n{proc.stderr}")
    line = next(
        l for l in proc.stdout.splitlines() if l.startswith("MESH_VALIDATE_JSON=")
    )
    report["mesh_validation"] = json.loads(line.split("=", 1)[1])
    report["mesh_validation"]["seconds"] = round(time.time() - t0, 1)
    report["total_seconds"] = round(time.time() - t_start, 1)

    # sanity. --quick's toy geometry stays near init (its pareto is noise),
    # so slope checks apply only to the full run; quick asserts the
    # pipeline contract (finite numbers, the expected report shape).
    for key_, pts in pareto.items():
        for p in pts:
            assert np.isfinite(p["fvu"]) and p["l0"] >= 0, (key_, p)
    if not quick:
        for key_, pts in pareto.items():
            assert pts[-1]["l0"] < pts[0]["l0"], (key_, pts)
        pts = pareto[f"layer{layer}_seed{seeds[0]}"]
        assert pts[-1]["fvu"] > pts[0]["fvu"], pts
        assert pts[0]["fvu"] < 0.9, ("low-l1 should beat unit FVU", pts)
    ident_loss = report["perplexity"]["under_reconstruction"][-1]["lm_loss"]
    assert abs(ident_loss - report["perplexity"]["base_lm_loss"]) < 1e-3

    out_prefix = Path(args.out) if args.out else REPO
    out_prefix.mkdir(parents=True, exist_ok=True)
    suffix = "_quick" if quick else ""
    json_path = out_prefix / f"PARITY_{ROUND_TAG}_dictpar{suffix}.json"
    with open(json_path, "w") as f:
        json.dump(report, f, indent=1)
    print(f"Wrote {json_path}")

    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    fig, ax = plt.subplots(figsize=(7, 5))
    for key_, pts in pareto.items():
        ax.plot([p["l0"] for p in pts], [p["fvu"] for p in pts], "o-",
                label=f"tied SAE r{RATIO} {key_}")
    ax.set_xlabel("mean L0 (active features/example)")
    ax.set_ylabel("FVU")
    ax.set_title(
        f"FVU vs L0 at dict ratio {RATIO} — residual layers {cap_layers}, "
        f"{report['config']['subject']}"
    )
    ax.legend()
    fig_path = out_prefix / f"parity_pareto_{ROUND_TAG}_dictpar{suffix}.png"
    fig.savefig(fig_path, dpi=150, bbox_inches="tight")
    print(f"Wrote {fig_path}")
    return report


if __name__ == "__main__":
    main()
