"""CLI shim: ``python -m sparse_coding__tpu.monitor <run_dir> [--once]``.

Tails a run directory's event logs (`events.jsonl` / per-process
`events.p<i>.jsonl`) and renders live throughput / health / straggler-skew
lines; ``--once`` prints one snapshot and exits nonzero on malformed event
lines. Implementation: `sparse_coding__tpu.telemetry.monitor`.
"""

from sparse_coding__tpu.telemetry.monitor import (
    EventTail,
    RunMonitor,
    TowerView,
    main,
    render,
    tower_render,
)

__all__ = [
    "EventTail", "RunMonitor", "TowerView", "main", "render", "tower_render",
]

if __name__ == "__main__":
    raise SystemExit(main())
