"""Dead-feature resurrection at the flagship 32x-overcomplete shape.

PARITY_r04_dictpar.json measured the science gap the dead-feature story
leaves open: at dict ratio 32 (n_dict=32768, pythia-410m-geometry mid-layer
residual) the tied SAE holds ~48% dead features at l1=1e-3 (>10 activations
over a 65k-row held-out sample). The reference's answer to exactly this is
worst-example resurrection (`/root/reference/experiments/huge_batch_size.py:
224-254`: re-init dead rows from the worst-reconstructed examples, reset
their Adam moments), rebuilt TPU-native in `train/big_batch.py` — but so far
only toy-tested.

This study trains the flagship shape twice on IDENTICAL data and batch
sequences (same PRNG stream; resurrection consumes no keys): a control arm
(no resurrection) and a resurrection arm (every `--reinit-every` steps), and
reports dead fraction / FVU / L0 for both, plus the per-event resurrection
log. Writes RESURRECT_<round>.json at the repo root.

Run: `python scripts/resurrect_study.py` (real chip, ~15-25 min incl.
pretrain+harvest). `--quick` is a CPU-sized smoke mode used by the tests.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from pathlib import Path

import numpy as np

REPO = Path(__file__).resolve().parent.parent
ROUND_TAG = os.environ.get("PARITY_ROUND", "r04")

if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))
sys.path.insert(0, str(REPO / "scripts"))


def main():
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--quick", action="store_true", help="CPU-sized smoke mode")
    ap.add_argument(
        "--pretrain", type=int, default=-1,
        help="trigram-pretrain steps (-1 = auto: 2000 full, 0 quick)",
    )
    ap.add_argument("--steps", type=int, default=None, help="train steps per arm")
    ap.add_argument(
        "--reinit-every", type=int, default=None,
        help="resurrection period in steps (resurrect arm only)",
    )
    ap.add_argument(
        "--norm-ratio", type=float, default=0.2,
        help="re-init row norm as a fraction of the average live-row norm "
        "(0.2 = the reference's convention)",
    )
    ap.add_argument(
        "--l1-warmup-steps", type=int, default=None,
        help="when set, the A/B becomes control vs l1-WARMUP (no "
        "resurrection in either arm): ramp l1_alpha linearly over this many "
        "steps — the anti-dead-feature lever LR_COLLAPSE r3 suggests, which "
        "the reference does not have",
    )
    ap.add_argument(
        "--tag", type=str, default="",
        help="suffix for the artifact filename (e.g. 'nr1' -> "
        "RESURRECT_<round>_nr1.json), so variant runs don't overwrite "
        "the main A/B",
    )
    ap.add_argument(
        "--out", type=str, default=None,
        help="output DIRECTORY for the RESURRECT_<round>.json artifact "
        "(default: repo root); created if missing",
    )
    args = ap.parse_args()
    if args.out and (Path(args.out).is_file() or Path(args.out).suffix == ".json"):
        # ADVICE r4: `--out RESURRECT.json` would otherwise mkdir a directory
        # of that name (the flag names a directory, not the artifact file) —
        # and it must fail HERE, not after a 15-25 min chip run. The suffix
        # check catches the common not-yet-existing `--out FOO.json` case.
        ap.error(f"--out must be a directory, got {args.out}")

    import jax
    import jax.numpy as jnp

    from dictpar_run import build_subject_model, subject_geometry
    from parity_run import SUBJECT_CAVEAT, corpus_tokens, maybe_pretrain
    from sparse_coding__tpu import metrics as sm
    from sparse_coding__tpu.data.activations import harvest_to_device
    from sparse_coding__tpu.models import FunctionalTiedSAE
    from sparse_coding__tpu.train.big_batch import train_big_batch

    t_start = time.time()
    quick = args.quick
    d_act, n_layers, _, _, layer = subject_geometry(quick)
    ratio = 32
    n_dict = ratio * d_act
    seq_len = 32 if quick else 256
    batch_rows = 16 if quick else 64
    chunk_gb = 0.002 if quick else 0.5
    n_chunks = 2 if quick else 8  # +1 held out for eval
    sae_batch = 256 if quick else 4096
    n_steps = args.steps if args.steps is not None else (40 if quick else 3000)
    reinit_every = (
        args.reinit_every if args.reinit_every is not None
        else (10 if quick else 400)
    )
    if n_steps < 1 or reinit_every < 1:
        ap.error("--steps and --reinit-every must be >= 1")
    if args.norm_ratio <= 0:
        # a zero-norm re-init (with encoder_bias also reset to 0) closes the
        # ReLU gate forever: the arm would run 15-25 min and mean nothing
        ap.error("--norm-ratio must be > 0")
    if args.l1_warmup_steps is not None and args.l1_warmup_steps < 1:
        # <1 would select warmup mode but never ramp: a control-vs-control
        # A/B silently labeled as a treatment
        ap.error("--l1-warmup-steps must be >= 1")
    l1_alpha = 1e-3
    lr = 3e-4  # dictpar_run: 1e-3 collapses high-l1 members at this shape
    dead_eval_rows = 2048 if quick else 65536
    eval_rows = 1024 if quick else 8192
    dead_threshold = 10

    pretrain_steps = args.pretrain if args.pretrain >= 0 else (0 if quick else 2000)
    print(f"Building subject model (pythia-410m geometry, d={d_act})...")
    lm_cfg, params = build_subject_model(quick)
    params, lang, pretrain_stats = maybe_pretrain(params, lm_cfg, quick, pretrain_steps)
    tokens = corpus_tokens(
        lang, lm_cfg.vocab_size, d_act, chunk_gb, batch_rows, seq_len,
        n_chunks + 1, seed=13,
    )

    report: dict = {
        "config": {
            "subject": f"neox d={d_act} L={n_layers} (pythia-410m geometry, "
            f"{'trigram-pretrained' if lang is not None else 'random init'})",
            "model": "FunctionalTiedSAE via train.big_batch (huge-batch DP trainer)",
            "layer": layer, "layer_loc": "residual", "seq_len": seq_len,
            "dict_ratio": ratio, "n_dict": n_dict, "l1_alpha": l1_alpha,
            "sae_batch": sae_batch, "n_steps": n_steps, "lr": lr,
            "reinit_every": reinit_every, "dead_threshold": dead_threshold,
            "encoder_norm_ratio": args.norm_ratio,
            "l1_warmup_steps": args.l1_warmup_steps,
            "device": jax.devices()[0].device_kind,
        },
        "subject_caveat": SUBJECT_CAVEAT,
        **({"pretrain": pretrain_stats} if pretrain_stats else {}),
    }

    print(f"Harvesting {n_chunks + 1} chunks (fused, device-resident)...")
    t0 = time.time()
    # scalar standardization at harvest: the FIRST chunk's std standardizes
    # every chunk — the same protocol as scripts/dictpar_run.py (which folds
    # the std into int8 dequant scales instead of materializing standardized
    # chunks; keep the two in sync if the protocol ever changes)
    chunks = []
    act_std = None
    eval_chunk = dead_eval = None
    for i, chunk in enumerate(harvest_to_device(
        params, lm_cfg, tokens, [layer], ["residual"],
        batch_size=batch_rows, chunk_size_gb=chunk_gb, n_chunks=n_chunks + 1,
    )):
        arr = chunk[(layer, "residual")]
        if act_std is None:
            act_std = float(arr.astype(jnp.float32).std())
        std_arr = arr.astype(jnp.float32) / act_std
        if i < n_chunks:
            chunks.append(std_arr.astype(jnp.bfloat16))
        else:
            dead_eval = std_arr[:dead_eval_rows]
            eval_chunk = std_arr[:eval_rows]
        del arr, std_arr
    dataset = jnp.concatenate(chunks)
    del chunks
    jax.device_get(dataset[0, 0])  # fence
    report["harvest"] = {
        "seconds": round(time.time() - t0, 1),
        "dataset_rows": int(dataset.shape[0]),
        "activation_std": act_std,
    }
    print(f"  {report['harvest']['seconds']:.0f}s, "
          f"{dataset.shape[0]:,} rows bf16-resident")

    # free the subject LM during training (it is not needed again: this
    # study evaluates dictionaries, not perplexity)
    params = None

    init_hp = dict(
        activation_size=d_act, n_dict_components=n_dict, l1_alpha=l1_alpha
    )
    # default A/B: control vs worst-example resurrection. With
    # --l1-warmup-steps: control vs l1-warmup, no resurrection in either arm
    # (arm spec = (name, reinit_every, l1_warmup_steps)).
    if args.l1_warmup_steps is not None:
        arm_specs = (
            ("control", None, 0), ("l1_warmup", None, args.l1_warmup_steps)
        )
    else:
        arm_specs = (("control", None, 0), ("resurrect", reinit_every, 0))
    arms = {}
    for arm, reinit, warmup in arm_specs:
        log: list = []
        t0 = time.time()
        state, sig = train_big_batch(
            FunctionalTiedSAE, init_hp, dataset,
            batch_size=sae_batch, n_steps=n_steps,
            key=jax.random.PRNGKey(0),  # identical batch sequence both arms
            learning_rate=lr, reinit_every=reinit,
            compute_dtype=None if quick else jnp.bfloat16,
            resurrection_log=log,
            encoder_norm_ratio=args.norm_ratio,
            l1_warmup_steps=warmup,
        )
        jax.block_until_ready(state.params["encoder"])
        train_s = time.time() - t0
        ld = sig.to_learned_dict(state.params, state.buffers)
        (row,) = sm.evaluate_dicts([ld], eval_chunk)
        n_alive = sm.batched_calc_feature_n_ever_active(
            ld, dead_eval, threshold=dead_threshold
        )
        n_dead = int(n_dict - n_alive)
        arms[arm] = {
            "train_seconds": round(train_s, 1),
            "rows_consumed": int(n_steps * sae_batch),
            "fvu": row["fvu"], "l0": row["l0"], "r2": row["r2"],
            "n_dead": n_dead, "n_feats": n_dict,
            "dead_fraction": round(n_dead / n_dict, 4),
            "dead_eval_rows": int(dead_eval.shape[0]),
            "resurrection_events": [
                {"step": int(s), "n_resurrected": int(n)} for s, n in log
            ],
        }
        del state, ld
        print(f"  {arm}: FVU {row['fvu']:.4f}, L0 {row['l0']:.1f}, "
              f"dead {n_dead}/{n_dict} ({arms[arm]['dead_fraction']:.1%}) "
              f"in {train_s:.0f}s")
    report["arms"] = arms
    treatment = arm_specs[1][0]  # "resurrect" or "l1_warmup"
    report["dead_fraction_delta"] = round(
        arms["control"]["dead_fraction"] - arms[treatment]["dead_fraction"], 4
    )
    report["total_seconds"] = round(time.time() - t_start, 1)

    # write the artifact BEFORE the sanity asserts: a failed assert must not
    # discard a 15-25 min chip run's diagnostics
    out_prefix = Path(args.out) if args.out else REPO
    out_prefix.mkdir(parents=True, exist_ok=True)
    tag = f"_{args.tag}" if args.tag else ""
    json_path = out_prefix / (
        f"RESURRECT_{ROUND_TAG}{tag}{'_quick' if quick else ''}.json"
    )
    with open(json_path, "w") as f:
        json.dump(report, f, indent=1)
    print(f"Wrote {json_path}")

    # sanity: both arms must train (FVU well below 1 — quick mode's 40-step
    # random-init run only checks finiteness); in resurrection mode the
    # treatment arm's events must actually have fired
    for arm in arms.values():
        assert np.isfinite(arm["fvu"]), arm
        if not quick:
            assert arm["fvu"] < 0.9, arm
    if treatment == "resurrect":
        assert arms["resurrect"]["resurrection_events"], "no resurrection fired"
    return report


if __name__ == "__main__":
    main()
