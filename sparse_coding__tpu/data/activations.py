"""LM activation harvesting → chunked activation store.

TPU-native counterpart of the reference `activation_dataset.py` (L0 of the
layer map). Differences by design (SURVEY.md §7 "hard parts" #1):

  - The reference runs the subject LM over batches of FOUR sentences
    (`MODEL_BATCH_SIZE=4`, `activation_dataset.py:37`) — its harvest
    bottleneck. Here the forward is one jitted program over large token
    batches, with every requested (layer, hook) captured in a single pass
    (the reference's multi-layer variant, `make_activation_dataset_hf`,
    `:326-391`) and early exit at the deepest requested layer.
  - Chunks are written through `data.chunks.save_chunk` (fp16 .npy), one
    folder per (layer, location), same `{i}` numbering and `skip_chunks`
    resume semantics (`:351-358`).
  - Long sequences: pass a mesh to shard the sequence axis with ring
    attention (`lm.ring_attention`) — the reference caps sequences at 256
    tokens (`:39`); we don't have to.

Tokenization follows the reference's GPT-style concatenate-and-chunk
(Nora Belrose's `chunk_and_tokenize`, `:139-238`): join documents with EOS,
split the token stream into exact `max_length` chunks, drop the ragged tail.
"""

from __future__ import annotations

import math
from functools import lru_cache, partial
from pathlib import Path
from typing import Callable, Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from sparse_coding__tpu.data.chunks import ChunkStore, save_chunk
from sparse_coding__tpu.lm import model as lm_model

MODEL_BATCH_SIZE = 64  # sentences per forward (vs the reference's 4)
MAX_SENTENCE_LEN = 256  # reference `activation_dataset.py:39`


# -- tokenization -------------------------------------------------------------

def chunk_tokens(token_stream: Sequence[int], max_length: int) -> np.ndarray:
    """Split one long token stream into exact-`max_length` rows, dropping the
    ragged tail (the reference drops its final batch too, `:205-208`)."""
    n = (len(token_stream) // max_length) * max_length
    return np.asarray(token_stream[:n], dtype=np.int32).reshape(-1, max_length)


def chunk_and_tokenize_texts(
    texts: Sequence[str],
    encode: Callable[[str], List[int]],
    eos_id: int,
    max_length: int = MAX_SENTENCE_LEN,
) -> np.ndarray:
    """GPT-style chunking: EOS-joined documents → `[n, max_length]` int32.

    `encode` is any text→ids callable (an HF tokenizer's `lambda t:
    tok(t)["input_ids"]`, or a test stub) — keeps this logic testable without
    network-fetched tokenizer files.
    """
    stream: List[int] = []
    for t in texts:
        stream.append(eos_id)
        stream.extend(encode(t))
    return chunk_tokens(stream, max_length)


def make_sentence_dataset(dataset_name: str, max_lines: int = 20_000, start_line: int = 0):
    """HF dataset load, sliced to [start_line, start_line+max_lines)
    (network / local cache; reference `:124-134`)."""
    from datasets import load_dataset

    return load_dataset(dataset_name, split=f"train[{start_line}:{start_line + max_lines}]")


def setup_token_data(dataset_name: str, tokenizer, max_length: int = MAX_SENTENCE_LEN,
                     max_lines: int = 20_000) -> np.ndarray:
    """Tokenized `[n, max_length]` rows from an HF dataset
    (reference `setup_token_data`, `activation_dataset.py:463-467`)."""
    ds = make_sentence_dataset(dataset_name, max_lines=max_lines)
    texts = ds["text"][:max_lines]
    return chunk_and_tokenize_texts(
        texts, lambda t: tokenizer(t)["input_ids"], tokenizer.eos_token_id, max_length
    )


# -- harvesting ---------------------------------------------------------------

@lru_cache(maxsize=16)
def _jitted_capture(
    lm_cfg: lm_model.LMConfig,
    names: Tuple[str, ...],
    stop_at: int,
    compute_dtype=None,
    attn: str = "dense",
):
    """One compiled capture forward per (config, hook set, dtype) — repeated
    `make_activation_dataset` calls in a process reuse the executable.

    Captured tensors are cast to fp16 ON DEVICE: the store is fp16 anyway
    (reference `:393-397`), and fetching half the bytes doubles effective
    device→host bandwidth — the harvest pipeline's non-compute cost.

    `compute_dtype=jnp.bfloat16` runs the subject forward in bf16 (params
    cast at trace time inside the program): measured +26% capture rate at
    pythia-410m geometry on one v5e (183k -> 230k tokens/s; the capture
    forward there is partly dispatch-bound, so the MXU win is diluted); the
    fp16 store quantizes harder than the bf16 error anyway for downstream
    SAE training. Default None is exact fp32."""

    if attn == "dense":
        attn_impl = lm_model.dense_attention
    elif attn == "blockwise":
        # single-chip long-context: O(S*block) memory flash-style recurrence
        from sparse_coding__tpu.lm.ring_attention import blockwise_attention

        attn_impl = blockwise_attention()
    else:
        raise ValueError(f"unknown single-device attn impl: {attn}")

    def f(p, t):
        # params arrive pre-cast (once per harvest, `_cast_params`); the
        # astype here is a traced no-op then, and only does work for direct
        # callers passing fp32 trees
        if compute_dtype is not None:
            p = _cast_params(p, compute_dtype)
        _, cache = lm_model.run_with_cache(
            p, t, lm_cfg, list(names), stop_at_layer=stop_at, attn_impl=attn_impl
        )
        return {k: v.astype(jnp.float16) for k, v in cache.items()}

    return jax.jit(f)


def _canon_dtype(compute_dtype):
    """Canonicalise a dtype spec ('bfloat16' / np.dtype / jnp.bfloat16 / None)
    so jit static args and lru_cache keys are identical for equal specs."""
    return jnp.dtype(compute_dtype) if compute_dtype is not None else None


def _cast_params(params, compute_dtype):
    """Cast the floating leaves of a param tree to `compute_dtype`."""
    return jax.tree.map(
        lambda x: x.astype(compute_dtype)
        if jnp.issubdtype(x.dtype, jnp.floating)
        else x,
        params,
    )


@partial(jax.jit, static_argnames=("compute_dtype",))
def _cast_params_jit(params, compute_dtype):
    """One-dispatch whole-tree cast: eager per-leaf `astype` would cost one
    tunneled dispatch per leaf (~hundreds for a 24-layer subject), swamping
    the bf16 win it exists to buy."""
    return _cast_params(params, compute_dtype)

def capture_fn(lm_cfg: lm_model.LMConfig, names: Sequence[str], stop_at: int,
               compute_dtype=None, attn: str = "dense"):
    """PUBLIC handle on the harvest pipeline's compiled capture forward
    (`_jitted_capture` — lru-cached, fp16-cast on device). The serving
    tier's fused ``/features`` path (`serve.engine`) runs THIS executable,
    so its activations are bit-identical to what `make_activation_dataset`
    / `harvest_to_device` produce for the same token batch — the
    harvest→encode fusion contract is structural, not numerical luck."""
    return _jitted_capture(
        lm_cfg, tuple(names), int(stop_at), _canon_dtype(compute_dtype), attn
    )


def _probe_activation_size(lm_cfg, name: str, stop_at: int, seq_len: int) -> int:
    """Width of an arbitrary qualified hook point, WITHOUT running the model:
    `jax.eval_shape` traces the capture forward on abstract values. This is
    what lets harvest accept any name `forward` emits (the baukit
    any-module analogue, reference `activation_dataset.py:292-298`) instead
    of only the four registered shorthands."""
    tok = jax.ShapeDtypeStruct((1, seq_len), jnp.int32)
    params = jax.eval_shape(lambda k: lm_model.init_params(k, lm_cfg), jax.random.PRNGKey(0))
    _, cache = jax.eval_shape(
        lambda p, t: lm_model.run_with_cache(p, t, lm_cfg, [name], stop_at_layer=stop_at),
        params, tok,
    )
    return int(cache[name].shape[-1])


def _harvest_plan(
    lm_cfg: lm_model.LMConfig,
    layers: Sequence[int],
    layer_locs: Sequence[str],
    chunk_size_gb: float,
    batch_size: int,
    seq_len: int,
):
    """Shared geometry for the disk and fused harvest paths: capture-point
    name map, early-exit layer, and how many capture batches fill one chunk
    (all points fill at the same row rate; the budget is the min)."""
    names = {
        (layer, loc): lm_model.make_tensor_name(layer, loc)
        for layer in layers
        for loc in layer_locs
    }
    stop_at = max(layers) + 1

    def width(loc, name):
        try:
            return lm_model.get_activation_size(lm_cfg, loc, seq_len=seq_len)
        except ValueError:
            # unregistered qualified name: size it by shape-probing the
            # forward (no compute, no compile)
            return _probe_activation_size(lm_cfg, name, stop_at, seq_len)

    chunk_rows = min(
        int(chunk_size_gb * 1024**3 // (width(loc, name) * 2))
        for (_, loc), name in names.items()
    )
    batches_per_chunk = max(1, chunk_rows // (batch_size * seq_len))
    return names, stop_at, batches_per_chunk


def _build_capture(
    lm_cfg, names: Dict, stop_at: int, mesh, seq_attn: str, compute_dtype=None,
    attn: str = "dense",
):
    """The compiled capture forward, single-device or sequence-parallel; both
    cast to fp16 ON DEVICE inside the jitted program (halved fetch bytes).
    `compute_dtype` (single-device path): bf16 subject forward, see
    `_jitted_capture`."""
    compute_dtype = _canon_dtype(compute_dtype)
    if compute_dtype is not None and mesh is not None:
        raise ValueError("compute_dtype is a single-device capture option")
    if attn != "dense" and mesh is not None:
        raise ValueError(
            "attn is a single-device capture option; with a mesh choose the "
            "sequence-parallel impl via seq_attn ('ring' | 'ulysses')"
        )
    if mesh is None:
        return _jitted_capture(
            lm_cfg, tuple(names.values()), stop_at, compute_dtype, attn
        )
    from sparse_coding__tpu.lm.ring_attention import make_sequence_parallel_fn

    # built ONCE: repeated calls reuse the compiled sharded program; the
    # fp16 cast is jitted AROUND seq_fn so XLA fuses it like the
    # single-device path
    seq_fn = make_sequence_parallel_fn(
        lm_cfg, mesh, cache_names=list(names.values()), stop_at_layer=stop_at,
        attn=seq_attn,
    )

    @jax.jit
    def capture(p, t):
        return {k: v.astype(jnp.float16) for k, v in seq_fn(p, t)[1].items()}

    return capture


def harvest_folder_name(base_folder, layer: int, layer_loc: str) -> Path:
    """One folder per (layer, location), reference layout `{base}_l{layer}_{loc}`
    (cf. `make_activation_dataset_hf` folder-per-layer, `:326-391`)."""
    return Path(f"{base_folder}_l{layer}_{layer_loc}")


# -- harvest cursor / verified resume -----------------------------------------

HARVEST_CURSOR = "sc_harvest_cursor.json"


def _harvest_config_sha(
    layers, layer_locs, batch_size, chunk_size_gb, store_dtype, center_dataset,
    tokens_shape,
) -> str:
    """Fingerprint of everything that determines chunk CONTENT at a given
    index — a resume against a store harvested under a different geometry
    must fail loudly, not silently splice incompatible chunks."""
    import hashlib
    import json as _json

    spec = {
        "layers": [int(l) for l in layers],
        "layer_locs": [str(l) for l in layer_locs],
        "batch_size": int(batch_size),
        "chunk_size_gb": float(chunk_size_gb),
        "store_dtype": str(store_dtype),
        "center_dataset": bool(center_dataset),
        "tokens_shape": [int(s) for s in tokens_shape],
    }
    return hashlib.sha256(_json.dumps(spec, sort_keys=True).encode()).hexdigest()[:16]


def _write_harvest_cursor(folders, next_chunk: int, batch_cursor: int, config_sha: str):
    """Commit the harvest position into every capture-point folder (atomic
    JSON replace) — each store is then self-describing for resume."""
    import time as _time

    from sparse_coding__tpu.data import integrity

    rec = {
        "format": 1,
        "chunk": int(next_chunk),
        "batch_cursor": int(batch_cursor),
        "config_sha": config_sha,
        "updated_at": _time.time(),
    }
    for folder in folders.values():
        integrity.write_json_atomic(Path(folder) / HARVEST_CURSOR, rec)


def read_harvest_cursor(folder) -> Optional[Dict]:
    import json as _json

    try:
        with open(Path(folder) / HARVEST_CURSOR) as f:
            return _json.load(f)
    except (OSError, ValueError):
        return None


def _verified_skip_chunks(folders, requested: int, config_sha: str) -> int:
    """How many leading chunks a resume may really skip: the longest prefix
    `[0, k)` (k ≤ `requested`) whose chunks VERIFY against their commit
    manifests in EVERY capture-point folder. `skip_chunks` used to trust
    bare file existence — a torn pair or a differently-configured store
    silently passed; now an unverifiable chunk truncates the skip (it gets
    re-harvested) and a cursor written under a different config fingerprint
    raises."""
    import warnings

    from sparse_coding__tpu.data import integrity
    from sparse_coding__tpu.telemetry.events import event_active

    for folder in folders.values():
        cursor = read_harvest_cursor(folder)
        if cursor is not None and cursor.get("config_sha") not in (None, config_sha):
            raise ValueError(
                f"harvest resume refused: {folder} was harvested under a "
                f"different configuration (cursor config_sha "
                f"{cursor.get('config_sha')!r} != {config_sha!r}); use a "
                "fresh dataset folder or re-harvest from scratch"
            )
    effective = requested
    for folder in folders.values():
        for i in range(requested):
            if i >= effective:
                break
            ok, reason = integrity.verify_chunk(folder, i)
            if not ok:
                effective = i
                warnings.warn(
                    f"harvest resume: chunk {i} in {folder} does not verify "
                    f"({reason}) — re-harvesting from chunk {i} instead of "
                    f"skipping {requested}",
                    RuntimeWarning,
                )
                event_active(
                    "anomaly", kind="harvest_resume_truncated", action="warn",
                    chunk=i, reason=reason, store=str(folder),
                )
                break
    return effective


def _committed_resume_point(folders, config_sha: str) -> int:
    """The cursor-recorded resume point, clamped to what actually verifies —
    a harvest killed mid-chunk resumes from the last *committed* chunk."""
    chunks = []
    for folder in folders.values():
        cursor = read_harvest_cursor(folder)
        chunks.append(0 if cursor is None else int(cursor.get("chunk", 0)))
    requested = min(chunks) if chunks else 0
    return _verified_skip_chunks(folders, requested, config_sha)


def make_activation_dataset(
    params,
    lm_cfg: lm_model.LMConfig,
    tokens: np.ndarray,
    dataset_folder: Union[str, Path],
    layers: Sequence[int],
    layer_locs: Sequence[str],
    batch_size: int = MODEL_BATCH_SIZE,
    chunk_size_gb: float = 2.0,
    n_chunks: Optional[int] = None,
    skip_chunks: int = 0,
    center_dataset: bool = False,
    mesh=None,
    seq_attn: str = "ring",
    single_folder: bool = False,
    compute_dtype=None,
    store_dtype=np.float16,
    attn: str = "dense",
    resume: bool = False,
    only_chunks: Optional[Sequence[int]] = None,
) -> Dict[Tuple[int, str], Path]:
    """Run the subject LM over `tokens` `[N, S]`, capturing every requested
    (layer, layer_loc) in one pass; write fp16 chunks per capture point.

    Returns {(layer, loc): folder}. `skip_chunks` resumes after a partial run
    (reference `:351-358`); `center_dataset` subtracts the first chunk's mean
    from all chunks (reference `:308-311, 379-381`); `mesh` switches the
    forward to sequence parallelism (`seq_attn`: "ring" | "ulysses",
    `lm.ring_attention`); `store_dtype=np.int8` ("int4") writes quantized
    chunks at half (a quarter of) the disk/transfer bytes, dequantized
    on device (`data.chunks`).

    **Resumable verified harvest** (docs/DATAPLANE.md): chunks are written
    through `data.chunks.save_chunk`'s atomic pair-commit, and after each
    chunk lands in every folder a harvest cursor
    (``sc_harvest_cursor.json``: next chunk, batch cursor, config
    fingerprint) is committed alongside. ``resume=True`` restarts from the
    last *committed* chunk — the cursor position clamped to the longest
    prefix that VERIFIES against its chunk manifests, so a harvest
    SIGKILLed mid-pair re-harvests the torn chunk instead of trusting it;
    a cursor from a differently-configured harvest raises. An explicit
    ``skip_chunks=N`` is verified the same way (it used to trust bare file
    existence) and is truncated, with a warning, at the first unverifiable
    chunk. ``only_chunks=[...]`` harvests exactly those indices (the batch
    cursor still advances deterministically through the rest), which is how
    `data.scrub --repair` refills quarantined holes bit-exactly.
    """
    names, stop_at, batches_per_chunk = _harvest_plan(
        lm_cfg, layers, layer_locs, chunk_size_gb, batch_size, tokens.shape[1]
    )

    if single_folder:
        assert len(names) == 1, "single_folder requires exactly one capture point"
        folders = {key: Path(dataset_folder) for key in names}
    else:
        folders = {
            (layer, loc): harvest_folder_name(dataset_folder, layer, loc)
            for layer, loc in names
        }
    for f in folders.values():
        f.mkdir(parents=True, exist_ok=True)

    config_sha = _harvest_config_sha(
        layers, layer_locs, batch_size, chunk_size_gb, store_dtype,
        center_dataset, tokens.shape,
    )
    if resume:
        # resume from the last committed-and-verified chunk (cursor clamped
        # by manifest verification); an explicit skip_chunks still wins when
        # it asks for LESS than the cursor reached
        committed = _committed_resume_point(folders, config_sha)
        skip_chunks = committed if skip_chunks == 0 else min(skip_chunks, committed)
    elif skip_chunks:
        skip_chunks = _verified_skip_chunks(folders, skip_chunks, config_sha)
    selected = None if only_chunks is None else {int(c) for c in only_chunks}

    compute_dtype = _canon_dtype(compute_dtype)
    capture = _build_capture(lm_cfg, names, stop_at, mesh, seq_attn, compute_dtype, attn)
    if compute_dtype is not None:
        params = _cast_params_jit(params, compute_dtype)  # pay the cast once

    n_batches_total = tokens.shape[0] // batch_size
    max_chunks = n_chunks if n_chunks is not None else math.inf

    chunk_idx = 0
    batch_cursor = 0
    means: Dict[Tuple[int, str], np.ndarray] = {}
    while chunk_idx < max_chunks and batch_cursor + batches_per_chunk <= n_batches_total:
        if chunk_idx < skip_chunks or (
            selected is not None and chunk_idx not in selected
        ):
            # resume/repair: skip the forward entirely, just advance the
            # cursor — chunk content is a pure function of the batch range
            batch_cursor += batches_per_chunk
            chunk_idx += 1
            continue
        buffers: Dict[Tuple[int, str], List[np.ndarray]] = {k: [] for k in names}

        def drain(cache):
            for key, name in names.items():
                act = cache[name]
                buffers[key].append(
                    np.asarray(jax.device_get(act)).reshape(-1, act.shape[-1])
                )

        # goodput spans (docs/observability.md §7): the harvest holds no
        # telemetry handle, so spans broadcast (the explicit ACTIVE
        # sentinel) to whatever RunTelemetry is live — e.g. the sweep's,
        # during init_model_dataset. The capture forward is the harvest's
        # productive window, the chunk-pair commit its checkpoint badput.
        # No live telemetry → two clock reads.
        from sparse_coding__tpu.telemetry.events import event_active
        from sparse_coding__tpu.telemetry.spans import ACTIVE, span as _span

        # 1-deep pipeline: dispatch the next forward before fetching the
        # previous batch's activations, overlapping device compute with the
        # device→host transfer (dispatch is async; device_get is the barrier)
        with _span(ACTIVE, "step", name="harvest_forward", chunk=chunk_idx):
            pending = None
            for b in range(batches_per_chunk):
                rows = tokens[(batch_cursor + b) * batch_size : (batch_cursor + b + 1) * batch_size]
                cache = capture(params, jnp.asarray(rows))
                if pending is not None:
                    drain(pending)
                pending = cache
            drain(pending)
        with _span(ACTIVE, "checkpoint", name="chunk_commit", chunk=chunk_idx):
            for key in names:
                chunk = np.concatenate(buffers[key], axis=0)
                if center_dataset:
                    if chunk_idx == 0 and key not in means:
                        means[key] = chunk.mean(axis=0)
                        np.save(folders[key] / "mean.npy", means[key])
                    elif key not in means:
                        means[key] = np.load(folders[key] / "mean.npy")
                    chunk = chunk - means[key]
                save_chunk(
                    folders[key], chunk_idx, chunk, dtype=store_dtype,
                    provenance={
                        "harvest": {
                            "config_sha": config_sha,
                            "layer": int(key[0]), "loc": str(key[1]),
                            "batches": [batch_cursor, batch_cursor + batches_per_chunk],
                            "centered": bool(center_dataset),
                        }
                    },
                )
                # lineage commit-point event (ISSUE 19): broadcast like the
                # spans above — joins the chunk to its harvest config in
                # whatever run's event log is live (no-op handle-less)
                event_active(
                    "provenance", artifact="chunk",
                    store=str(folders[key]), chunk=int(chunk_idx),
                    config_sha=config_sha,
                )
            batch_cursor += batches_per_chunk
            chunk_idx += 1
            if selected is None:
                # commit the harvest position AFTER the chunk landed in every
                # folder — the resume contract "last committed chunk" (repair
                # passes leave the cursor alone: they fill holes, not the tail)
                _write_harvest_cursor(folders, chunk_idx, batch_cursor, config_sha)

    return folders


def harvest_to_device(
    params,
    lm_cfg: lm_model.LMConfig,
    tokens: np.ndarray,
    layers: Sequence[int],
    layer_locs: Sequence[str],
    batch_size: int = MODEL_BATCH_SIZE,
    chunk_size_gb: float = 2.0,
    n_chunks: Optional[int] = None,
    mesh=None,
    seq_attn: str = "ring",
    save_folder: Optional[Union[str, Path]] = None,
    compute_dtype=None,
    store_dtype=np.float16,
    attn: str = "dense",
):
    """Fused harvest→train streaming: yield HBM-resident activation chunks,
    never round-tripping through the host.

    `make_activation_dataset` exists for the reference's on-disk data contract
    (`activation_dataset.py:393-397`) — but when the chunks are consumed by
    training on the same chip(s), fetching them to host only to re-upload
    costs two PCIe/tunnel crossings per chunk for nothing. This generator is
    the design SURVEY.md §7 ("hard parts" #1) calls for: the capture forward
    and the consuming train step share HBM; the only host work is feeding
    token ids (tiny). Yields ``{(layer, loc): [rows, d_loc] fp16 device
    array}`` per chunk — the same values `make_activation_dataset` would have
    written (asserted in tests).

    ``save_folder``: optionally ALSO persist each chunk through the normal
    `.npy` store (pays the device→host fetch; keeps the data contract when
    the run should be resumable/reusable). ``store_dtype`` selects the
    persisted tier exactly as in `make_activation_dataset` — fp16
    (default), ``np.int8``, or ``"int4"`` — so fused-harvest runs can
    persist quantized stores too (the yielded device chunks stay fp16
    either way; quantization is a disk/transfer format, not a training
    dtype).
    """
    names, stop_at, batches_per_chunk = _harvest_plan(
        lm_cfg, layers, layer_locs, chunk_size_gb, batch_size, tokens.shape[1]
    )
    compute_dtype = _canon_dtype(compute_dtype)
    capture = _build_capture(lm_cfg, names, stop_at, mesh, seq_attn, compute_dtype, attn)
    if compute_dtype is not None:
        params = _cast_params_jit(params, compute_dtype)  # pay the cast once

    folders = None
    if save_folder is not None:
        folders = {
            (layer, loc): harvest_folder_name(save_folder, layer, loc)
            for (layer, loc) in names
        }
        for f in folders.values():
            f.mkdir(parents=True, exist_ok=True)

    n_batches_total = tokens.shape[0] // batch_size
    max_chunks = n_chunks if n_chunks is not None else math.inf

    chunk_idx = 0
    batch_cursor = 0
    while chunk_idx < max_chunks and batch_cursor + batches_per_chunk <= n_batches_total:
        buffers: Dict[Tuple[int, str], List[jax.Array]] = {k: [] for k in names}
        for b in range(batches_per_chunk):
            rows = tokens[(batch_cursor + b) * batch_size : (batch_cursor + b + 1) * batch_size]
            cache = capture(params, jnp.asarray(rows))
            for key, name in names.items():
                act = cache[name]
                buffers[key].append(act.reshape(-1, act.shape[-1]))
        chunk = {
            key: jnp.concatenate(parts, axis=0) for key, parts in buffers.items()
        }
        # free the per-batch parts BEFORE yielding: the paused generator would
        # otherwise keep a second full copy of the chunk alive in HBM for the
        # whole consuming train step
        del buffers
        if folders is not None:
            for key, arr in chunk.items():
                save_chunk(
                    folders[key], chunk_idx, np.asarray(jax.device_get(arr)),
                    dtype=store_dtype,
                )
        yield chunk
        batch_cursor += batches_per_chunk
        chunk_idx += 1


def setup_data(
    model_name: str,
    dataset_name: str,
    dataset_folder: Union[str, Path],
    layer: Union[int, Sequence[int]],
    layer_loc: Union[str, Sequence[str]] = "residual",
    n_chunks: int = 30,
    chunk_size_gb: float = 2.0,
    center_dataset: bool = False,
    max_length: int = MAX_SENTENCE_LEN,
    batch_size: int = MODEL_BATCH_SIZE,
    max_lines: int = 100_000,
    skip_chunks: int = 0,
    compute_dtype=None,
    store_dtype="float16",
    resume: bool = False,
) -> int:
    """Full pipeline: HF model + dataset → tokenize → harvest → chunk store
    (reference `setup_data`, `activation_dataset.py:400-460`). Needs the HF
    model/dataset locally cached or network access. Returns n_datapoints.
    ``compute_dtype="bfloat16"`` runs the capture forward in bf16 (see
    `_jitted_capture`)."""
    # resolve the dtype BEFORE the expensive model load/tokenize: a typo'd
    # string should fail in milliseconds, not minutes into the run
    compute_dtype = _canon_dtype(compute_dtype)
    import transformers

    from sparse_coding__tpu.lm.convert import _canonical_hf_name, load_model

    lm_cfg, params = load_model(model_name)
    tok_name = model_name if "/" in model_name else _canonical_hf_name(model_name)
    tokenizer = transformers.AutoTokenizer.from_pretrained(tok_name)
    tokens = setup_token_data(dataset_name, tokenizer, max_length=max_length, max_lines=max_lines)

    layers = [layer] if isinstance(layer, int) else list(layer)
    locs = [layer_loc] if isinstance(layer_loc, str) else list(layer_loc)
    single = len(layers) == 1 and len(locs) == 1
    folders = make_activation_dataset(
        params, lm_cfg, tokens, dataset_folder, layers, locs,
        batch_size=batch_size, chunk_size_gb=chunk_size_gb, n_chunks=n_chunks,
        skip_chunks=skip_chunks, center_dataset=center_dataset,
        single_folder=single,
        resume=resume,
        compute_dtype=compute_dtype,
        # "int4" is a save_chunk format tag, not a numpy dtype
        store_dtype=store_dtype if str(store_dtype) == "int4" else np.dtype(store_dtype),
    )
    return sum(ChunkStore(f).n_datapoints() for f in folders.values())


def main(argv=None):
    """CLI: `python -m sparse_coding__tpu.data.activations --layers 2 3 ...`
    (reference `generate_test_data.py:13-50`)."""
    import argparse

    p = argparse.ArgumentParser(description="Generate LM activation chunks")
    p.add_argument("--model_name", default="EleutherAI/pythia-70m-deduped")
    p.add_argument("--dataset_name", default="NeelNanda/pile-10k")
    p.add_argument("--dataset_folder", required=True)
    p.add_argument("--layers", type=int, nargs="+", required=True)
    p.add_argument("--layer_locs", nargs="+", default=["residual"])
    p.add_argument("--n_chunks", type=int, default=10)
    p.add_argument("--chunk_size_gb", type=float, default=2.0)
    p.add_argument("--center_dataset", action="store_true")
    p.add_argument("--skip_chunks", type=int, default=0)
    p.add_argument("--resume", action="store_true",
                   help="resume from the last committed-and-verified chunk "
                   "(sc_harvest_cursor.json; docs/DATAPLANE.md)")
    p.add_argument("--compute_dtype", default=None,
                   help="e.g. bfloat16: run the capture forward MXU-native")
    p.add_argument("--store_dtype", default="float16",
                   choices=("float16", "int8", "int4"),
                   help="chunk store format; int8 halves / int4 quarters the "
                   "disk/transfer bytes (per-row absmax, on-device dequant)")
    args = p.parse_args(argv)
    n = setup_data(
        args.model_name, args.dataset_name, args.dataset_folder,
        layer=args.layers, layer_loc=args.layer_locs, n_chunks=args.n_chunks,
        chunk_size_gb=args.chunk_size_gb, center_dataset=args.center_dataset,
        skip_chunks=args.skip_chunks, compute_dtype=args.compute_dtype,
        store_dtype=args.store_dtype, resume=args.resume,
    )
    print(f"wrote {n} datapoints")


if __name__ == "__main__":
    main()
