"""Compressed Adam second-moment storage (`utils/optim.py`, nu_dtype=bfloat16).

Covers the three claims the design rests on (module doc of utils/optim.py):
unbiased stochastic rounding, the round-to-nearest EMA freeze it prevents,
and training parity vs fp32-nu Adam — on both the XLA path and the fused
Pallas kernel in interpret mode. NOTE: interpret mode exercises the
counter-hash bit stream; the compiled kernel uses the on-core hardware PRNG,
a DIFFERENT (equally unbiased, equally deterministic-per-step) stream — the
statistical assertions here transfer, bit-level values do not. The compiled
stream's loss parity is measured on-chip (THROUGHPUT.md §r4d).
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from sparse_coding__tpu.ensemble import Ensemble, stack_pytrees
from sparse_coding__tpu.models import FunctionalTiedSAE
from sparse_coding__tpu.utils import optim

D, N, B, M = 128, 512, 256, 2


def _stacked(key=0):
    models = [
        FunctionalTiedSAE.init(k, D, N, l1_alpha=a, bias_decay=1e-4)
        for k, a in zip(jax.random.split(jax.random.PRNGKey(key), M), [1e-3, 3e-3])
    ]
    params = stack_pytrees([p for p, _ in models])
    params["encoder_bias"] = 0.01 * jax.random.normal(jax.random.PRNGKey(5), (M, N))
    buffers = stack_pytrees([b for _, b in models])
    batch = jax.random.normal(jax.random.PRNGKey(1), (B, D))
    return params, buffers, batch


def test_stochastic_round_unbiased():
    x = jnp.full((50_000,), 1.00123, jnp.float32)
    r = optim.stochastic_round(x, jax.random.PRNGKey(0), jnp.bfloat16)
    vals = np.unique(np.asarray(r, np.float32))
    # rounds only to the two neighboring bf16 values...
    assert set(vals) <= {1.0, 1.0078125}
    # ...with the mean recovering the f32 value (unbiasedness)
    assert abs(float(r.astype(jnp.float32).mean()) - 1.00123) < 2e-4
    # non-finite passthrough
    bad = jnp.asarray([jnp.inf, -jnp.inf, jnp.nan], jnp.float32)
    rb = optim.stochastic_round(bad, jax.random.PRNGKey(1), jnp.bfloat16)
    assert np.isinf(np.asarray(rb)[0]) and np.isnan(np.asarray(rb, np.float32)[2])


def test_deterministic_bf16_ema_freezes_stochastic_tracks():
    """The reason nu_dtype needs stochastic rounding: a round-to-nearest bf16
    EMA of g²=1 freezes far below its target; the stochastic store tracks."""
    b2 = 0.999

    @jax.jit
    def run():
        def body(t, carry):
            det, sr, k = carry
            det = ((1 - b2) * 1.0 + b2 * det.astype(jnp.float32)).astype(jnp.bfloat16)
            k, sk = jax.random.split(k)
            sr = optim.stochastic_round(
                (1 - b2) * 1.0 + b2 * sr.astype(jnp.float32), sk, jnp.bfloat16
            )
            return det, sr, k

        return jax.lax.fori_loop(
            0,
            4000,
            body,
            (jnp.zeros((), jnp.bfloat16), jnp.zeros((1,), jnp.bfloat16), jax.random.PRNGKey(1)),
        )

    det, sr, _ = run()
    target = 1 - b2**4000  # 0.9817
    assert float(det) < 0.5, "expected the deterministic-rounded EMA to freeze"
    assert abs(float(sr[0]) - target) < 0.05 * target


def test_adam_without_nu_dtype_is_optax_adam():
    tx = optim.adam(1e-3, mu_dtype=jnp.bfloat16)
    ref = optax.adam(1e-3, mu_dtype=jnp.bfloat16)
    p = {"w": jnp.linspace(0.0, 1.0, 64).reshape(8, 8)}
    g = {"w": jnp.full((8, 8), 0.1)}
    s, sr = tx.init(p), ref.init(p)
    for _ in range(3):
        u, s = tx.update(g, s, p)
        ur, sr = ref.update(g, sr, p)
    assert jnp.array_equal(u["w"], ur["w"])
    assert jnp.array_equal(s[0].nu["w"], sr[0].nu["w"])


def test_compressed_adam_tracks_f32_adam():
    tx_f32 = optim.adam(1e-3)
    tx_bf = optim.adam(1e-3, nu_dtype=jnp.bfloat16)
    p0 = {"w": jnp.ones((64, 64))}

    def run(tx):
        def body(t, carry):
            p, s = carry
            g = {"w": 0.1 * jnp.cos(t / 10.0) * jnp.ones((64, 64)) + 0.01 * jnp.sin(t * 1.7)}
            u, s = tx.update(g, s, p)
            return optax.apply_updates(p, u), s

        return jax.jit(lambda: jax.lax.fori_loop(0, 300, body, (p0, tx.init(p0))))()

    (p_f, s_f), (p_b, s_b) = run(tx_f32), run(tx_bf)
    assert s_b[0].nu["w"].dtype == jnp.bfloat16
    assert float(jnp.abs(p_f["w"] - p_b["w"]).max()) < 5e-3
    rel = jnp.abs(s_b[0].nu["w"].astype(jnp.float32) - s_f[0].nu["w"]) / (
        s_f[0].nu["w"] + 1e-12
    )
    assert float(rel.mean()) < 0.05


def test_fused_adam_step_bf16_nu_interpret():
    """Kernel contract for nu_dtype=bfloat16 (interpret mode, counter-hash
    stream): step 1 param update is BIT-CLOSE to the f32-nu control (the
    update always uses the unrounded f32 EMA; only storage rounds), the
    stored nu is within one bf16 ulp of the f32 value, and the rounding is
    deterministic given the step count."""
    params, buffers, batch = _stacked()
    tx_f32 = optim.adam(1e-3)
    tx_bf = optim.adam(1e-3, nu_dtype=jnp.bfloat16)
    os_f32 = jax.vmap(tx_f32.init)(params)
    os_bf = jax.vmap(tx_bf.init)(params)
    assert os_bf[0].nu["encoder"].dtype == jnp.bfloat16

    pf, osf, _ = FunctionalTiedSAE.fused_adam_step(
        params, buffers, batch, os_f32, 1e-3, 0.9, 0.999, 1e-8, interpret=True
    )
    pb, osb, _ = FunctionalTiedSAE.fused_adam_step(
        params, buffers, batch, os_bf, 1e-3, 0.9, 0.999, 1e-8, interpret=True
    )
    pb2, osb2, _ = FunctionalTiedSAE.fused_adam_step(
        params, buffers, batch, jax.vmap(tx_bf.init)(params),
        1e-3, 0.9, 0.999, 1e-8, interpret=True,
    )
    for k in ["encoder", "encoder_bias"]:
        a, b = np.asarray(pf[k]), np.asarray(pb[k])
        assert np.abs(a - b).max() / (np.abs(a).max() + 1e-8) < 1e-5, k
        # storage within one rounding of the f32 value, unbiased on average
        nf = np.asarray(osf[0].nu[k], np.float32)
        nb = np.asarray(osb[0].nu[k], np.float32)
        rel = np.abs(nb - nf) / (np.abs(nf) + 1e-20)
        assert rel.max() < 2 ** -7 + 1e-6, k
        assert abs(np.mean((nb - nf) / (np.abs(nf) + 1e-20))) < 2e-3, k
        # deterministic stream: same step count -> identical rounded state
        assert np.array_equal(nb, np.asarray(osb2[0].nu[k], np.float32)), k


def test_fused_adam_bf16_nu_multi_step_tracks(stacked_steps=25):
    """After many fused steps the bf16-nu trajectory stays near the f32-nu
    control: nu mean rel err a few %, params close."""
    params, buffers, batch = _stacked()
    key = jax.random.PRNGKey(9)

    def run(nu_dtype):
        tx = optim.adam(1e-3, nu_dtype=nu_dtype)
        os_ = jax.vmap(tx.init)(params)
        p = params
        for t in range(stacked_steps):
            bt = jax.random.normal(jax.random.fold_in(key, t), (B, D))
            p, os_, _ = FunctionalTiedSAE.fused_adam_step(
                p, buffers, bt, os_, 1e-3, 0.9, 0.999, 1e-8, interpret=True
            )
        return p, os_

    (pf, osf), (pb, osb) = run(None), run(jnp.bfloat16)
    nf = np.asarray(osf[0].nu["encoder"], np.float32)
    nb = np.asarray(osb[0].nu["encoder"], np.float32)
    assert np.mean(np.abs(nb - nf) / (np.abs(nf) + 1e-20)) < 0.05
    a, b = np.asarray(pf["encoder"]), np.asarray(pb["encoder"])
    assert np.abs(a - b).max() / (np.abs(a).max() + 1e-12) < 5e-3


def test_ensemble_trains_with_bf16_nu_and_roundtrips():
    """End-to-end: Ensemble(optimizer_kwargs={'nu_dtype': 'bfloat16'}) trains
    on the XLA path, loss decreases, and the checkpoint round-trip preserves
    the compressed state dtype."""
    key = jax.random.PRNGKey(3)
    models = [
        FunctionalTiedSAE.init(k, 32, 64, l1_alpha=1e-4, bias_decay=0.0)
        for k in jax.random.split(key, 2)
    ]
    ens = Ensemble(
        models,
        FunctionalTiedSAE,
        optimizer="adam",
        optimizer_kwargs={"learning_rate": 1e-3, "nu_dtype": "bfloat16"},
    )
    assert ens.state.opt_state[0].nu["encoder"].dtype == jnp.bfloat16
    data = jax.random.normal(jax.random.PRNGKey(4), (100, 256, 32))
    first = last = None
    for i in range(100):
        ld, _ = ens.step_batch(data[i])
        if i == 0:
            first = float(ld["loss"].mean())
    last = float(ld["loss"].mean())
    assert last < first * 0.7, (first, last)

    sd = ens.state_dict()
    ens2 = Ensemble.from_state(sd)
    assert ens2.state.opt_state[0].nu["encoder"].dtype == jnp.bfloat16
    ld2, _ = ens2.step_batch(data[0])
    assert np.isfinite(float(ld2["loss"].mean()))


# -- int8 moment storage (QuantMoment tier, round 6) -------------------------

def test_quantize_rows_stochastic_unbiased_and_exact_scale():
    """The int8 store is unbiased (E[dequant] == x) and uses the chunk-store
    scale math (absmax/127, all-zero rows scale 1)."""
    x = jnp.tile(jnp.asarray([[0.5, -1.0, 0.01234, 0.0]]), (20_000, 1))
    qm = optim.quantize_rows_stochastic(x, jax.random.PRNGKey(0))
    assert qm.q.dtype == jnp.int8 and qm.scale.shape == (20_000,)
    np.testing.assert_allclose(np.asarray(qm.scale), 1.0 / 127.0, rtol=1e-6)
    mean = np.asarray(qm.dequant()).mean(axis=0)
    np.testing.assert_allclose(mean, np.asarray(x[0]), atol=3e-4)
    # all-zero row: scale 1, dequant exact
    z = optim.quantize_rows_stochastic(jnp.zeros((2, 8)), jax.random.PRNGKey(1))
    assert float(z.scale[0]) == 1.0
    np.testing.assert_array_equal(np.asarray(z.dequant()), 0.0)


def test_int8_adam_tracks_f32_adam():
    """Training with int8-stored moments tracks fp32 Adam the way bf16-nu
    does: same trajectory within the storage-noise envelope (bulk of params
    close; loss curve equivalent)."""
    params, buffers, batch = _stacked()
    tx32 = optim.adam(1e-3)
    tx8 = optim.adam(1e-3, mu_dtype="int8", nu_dtype="bfloat16")
    s32 = jax.vmap(tx32.init)(params)
    s8 = jax.vmap(tx8.init)(params)
    assert isinstance(s8[0].mu["encoder"], optim.QuantMoment)
    # 1-D leaves stay fp32 under the int8 policy (no row axis to scale)
    assert s8[0].mu["encoder_bias"].dtype == jnp.float32

    grad_fn = jax.vmap(jax.grad(FunctionalTiedSAE.loss, has_aux=True), in_axes=(0, 0, None))
    p32, p8 = params, params
    for _ in range(20):
        g32, _ = grad_fn(p32, buffers, batch)
        u32, s32 = jax.vmap(tx32.update)(g32, s32, p32)
        p32 = optax.apply_updates(p32, u32)
        g8, _ = grad_fn(p8, buffers, batch)
        u8, s8 = jax.vmap(tx8.update)(g8, s8, p8)
        p8 = optax.apply_updates(p8, u8)
    for k in ["encoder", "encoder_bias"]:
        diff = np.abs(np.asarray(p32[k]) - np.asarray(p8[k]))
        assert np.median(diff) < 2e-3, k  # ~2 lr of bulk drift over 20 steps
        assert np.isfinite(np.asarray(p8[k])).all(), k
    # moments stayed compressed the whole way
    assert s8[0].mu["encoder"].q.dtype == jnp.int8


def test_int8_state_checkpoint_roundtrip():
    """QuantMoment survives device_get + re-asarray (the checkpoint path:
    `Ensemble.state_dict` / `from_state` traverse it as a pytree)."""
    params, _buffers, _batch = _stacked()
    tx = optim.adam(1e-3, mu_dtype="int8", nu_dtype="int8")
    st = jax.vmap(tx.init)(params)
    host = jax.device_get(st)
    back = jax.tree.map(jnp.asarray, host)
    assert isinstance(back[0].mu["encoder"], optim.QuantMoment)
    g = jax.tree.map(lambda p: jnp.ones_like(p) * 0.01, params)
    upd, _ = jax.vmap(tx.update)(g, back, params)  # restored state steps
    assert np.isfinite(np.asarray(upd["encoder"])).all()


def test_adam_eps_root_passthrough_changes_update():
    """`eps_root` routes through the compressed implementation and changes
    the update (the fused-Adam whitelist refuses it; the optax fallback
    must actually honor it)."""
    g = {"w": jnp.ones((4, 8)) * 1e-4}
    p = {"w": jnp.zeros((4, 8))}
    tx0 = optim.adam(1e-3)
    tx1 = optim.adam(1e-3, eps_root=1e-2)
    u0, _ = tx0.update(g, tx0.init(p), p)
    u1, _ = tx1.update(g, tx1.init(p), p)
    assert not np.allclose(np.asarray(u0["w"]), np.asarray(u1["w"]))
