"""Finding records and the baseline (grandfathered-findings) format."""

from __future__ import annotations

import dataclasses
from typing import Any, Dict


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation at one source location.

    ``path`` is repo-relative and POSIX-style, so keys are stable across
    checkouts. ``key`` (rule:path:line) is the baseline identity: coarse
    enough to survive edits elsewhere in the file's history being re-keyed,
    precise enough that a *new* violation of the same rule in the same file
    still fails the gate.
    """

    rule: str
    path: str
    line: int
    col: int
    message: str

    @property
    def key(self) -> str:
        return f"{self.rule}:{self.path}:{self.line}"

    def to_json(self) -> Dict[str, Any]:
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "message": self.message,
            "key": self.key,
        }

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule} {self.message}"

    def sort_key(self):
        return (self.path, self.line, self.col, self.rule)
