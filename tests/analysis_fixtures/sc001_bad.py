"""Fixture: SC001 violation — floating-ness tested via dtype.kind.

Never imported; parsed by tests/test_analysis.py, which pins each finding
to the marker-comment line.
"""


def keep_resident(x):
    if x.dtype.kind == "f":  # VIOLATION
        return x.astype("bfloat16")
    return x
