"""Self-healing activation data plane (ISSUE 8, docs/DATAPLANE.md).

Four tiers:

  - **unit** — atomic chunk-pair commit + manifests, verify tiers
    (size/digest/off), quarantine moves, the silent-misread regressions
    (fp16-over-int8 gap, missing scale file), `n_datapoints` via manifests
    and the public npy-header API, loss-budget accounting;
  - **driver degraded mode** — `basic_l1_sweep`/`sweep`/`train_big_batch`
    survive a corrupt chunk inside `SC_CHUNK_LOSS_BUDGET` (skip-and-account,
    telemetry counters, report/monitor rendering) and exit 75 past it;
  - **tooling** — the scrub CLI against the checked-in
    `tests/golden/corrupt_store/` fixture (report rendering + exit codes
    pinned) and synthetic-store repair; fleet admission-check requeue;
  - **chaos acceptance** (tier-1, ``chaos`` marker) — harvest SIGKILLed
    mid-chunk-pair via SC_FAULT, store bit-flipped post-hoc → scrub
    quarantines exactly the bad chunk, resumed harvest + `only_chunks`
    repair restore the store bit-exactly, training over it matches an
    uncorrupted control, and a degraded-mode run over the UNREPAIRED store
    finishes inside budget with the loss accounted.
"""

import json
import os
import shutil
import subprocess
import sys
from pathlib import Path

import jax
import numpy as np
import pytest

from sparse_coding__tpu.data import (
    ChunkStore,
    RandomDatasetGenerator,
    save_chunk,
)
from sparse_coding__tpu.data import integrity
from sparse_coding__tpu.data.chunks import chunk_path, scale_path
from sparse_coding__tpu.data.scrub import (
    render_scrub_markdown,
    scrub_store,
    store_loss,
)
from sparse_coding__tpu.telemetry import RunTelemetry
from sparse_coding__tpu.train import preemption
from sparse_coding__tpu.utils import faults

REPO = Path(__file__).resolve().parent.parent
GOLDEN_STORE = Path(__file__).parent / "golden" / "corrupt_store"


@pytest.fixture(autouse=True)
def _clean_state(monkeypatch):
    monkeypatch.delenv(faults.FAULT_ENV, raising=False)
    monkeypatch.delenv(integrity.CHUNK_VERIFY_ENV, raising=False)
    monkeypatch.delenv(integrity.LOSS_BUDGET_ENV, raising=False)
    monkeypatch.setenv("SC_SYNC_BACKOFF", "0")
    faults.reset()
    preemption.reset()
    yield
    faults.reset()
    preemption.reset()


def _data(rows=64, d=16, seed=0):
    return np.random.default_rng(seed).normal(size=(rows, d)).astype(np.float32)


def _bitflip(path: Path):
    raw = bytearray(path.read_bytes())
    raw[-1] ^= 0xFF
    path.write_bytes(bytes(raw))


def _truncate(path: Path, n=32):
    path.write_bytes(path.read_bytes()[:-n])


# -- atomic commit + manifests ------------------------------------------------

def test_commit_writes_manifest_with_digests(tmp_path):
    a = _data()
    save_chunk(tmp_path, 0, a)
    save_chunk(tmp_path, 1, a, dtype=np.int8)
    m0 = integrity.read_chunk_manifest(tmp_path, 0)
    m1 = integrity.read_chunk_manifest(tmp_path, 1)
    assert m0["rows"] == 64 and m0["store_dtype"] == "float16"
    assert set(m0["files"]) == {"0.npy"}
    assert set(m1["files"]) == {"1.npy", "1.scale.npy"}
    assert m1["store_dtype"] == "int8"
    for meta in m1["files"].values():
        assert meta["bytes"] > 0 and len(meta["sha256"]) == 64
    assert integrity.verify_chunk(tmp_path, 0, depth="digest") == (True, "ok")
    assert integrity.verify_chunk(tmp_path, 1, depth="digest") == (True, "ok")
    # manifest-driven row counting, no data read
    assert ChunkStore(tmp_path).n_datapoints() == 128


def test_n_datapoints_legacy_public_header(tmp_path):
    """Legacy stores (no manifests) count rows through the PUBLIC numpy
    header API — the private `_read_array_header` broke across versions."""
    np.save(chunk_path(tmp_path, 0), _data(rows=48).astype(np.float16))
    np.save(chunk_path(tmp_path, 1), _data(rows=16).astype(np.float16))
    assert ChunkStore(tmp_path).n_datapoints() == 64


def test_provenance_recorded(tmp_path):
    save_chunk(tmp_path, 0, _data(), provenance={"harvest": {"layer": 3}})
    m = integrity.read_chunk_manifest(tmp_path, 0)
    assert m["provenance"]["harvest"]["layer"] == 3


# -- verify tiers + quarantine ------------------------------------------------

def test_verify_tiers_and_quarantine(tmp_path):
    a = _data()
    save_chunk(tmp_path, 0, a)
    _bitflip(chunk_path(tmp_path, 0))  # size intact, digest wrong
    assert integrity.verify_chunk(tmp_path, 0, depth="size") == (True, "ok")
    ok, reason = integrity.verify_chunk(tmp_path, 0, depth="digest")
    assert not ok and "digest mismatch" in reason

    save_chunk(tmp_path, 1, a)
    _truncate(chunk_path(tmp_path, 1))  # size wrong: the default tier catches
    ok, reason = integrity.verify_chunk(tmp_path, 1)  # env default = size
    assert not ok and "size mismatch" in reason

    telemetry = RunTelemetry(out_dir=None)
    try:
        with pytest.raises(integrity.CorruptChunk) as e:
            ChunkStore(tmp_path).load(1)
        assert e.value.chunk == 1
        # quarantined, not deleted: files moved with a reason record
        assert not chunk_path(tmp_path, 1).exists()
        assert (tmp_path / "quarantine" / "1.npy").exists()
        assert integrity.quarantined_indices(tmp_path) == [1]
        assert integrity.quarantined_rows(tmp_path, 1) == 64
        assert telemetry.counters.get("data.corrupt") == 1
        # a later load of the quarantined index is CorruptChunk, not
        # FileNotFoundError — the hole is data loss, not a caller bug
        with pytest.raises(integrity.CorruptChunk, match="quarantined"):
            ChunkStore(tmp_path).load(1)
    finally:
        telemetry.close()
    # slot_count keeps the quarantined chunk's place; len drops it
    st = ChunkStore(tmp_path)
    assert len(st) == 1 and st.slot_count() == 2


def test_verified_load_counts(tmp_path):
    save_chunk(tmp_path, 0, _data())
    telemetry = RunTelemetry(out_dir=None)
    try:
        ChunkStore(tmp_path).load(0)
        assert telemetry.counters.get("data.chunks_verified") == 1
    finally:
        telemetry.close()


def test_missing_index_stays_file_not_found(tmp_path):
    save_chunk(tmp_path, 0, _data())
    with pytest.raises(FileNotFoundError):
        ChunkStore(tmp_path).load(7)


# -- the silent-misread regressions -------------------------------------------

def test_missing_scale_detected_not_misread(tmp_path):
    """The pre-fix failure: int8 chunk bytes with no scale file were loaded
    as RAW INTEGERS and fed to training. Pinned as *detected* — CorruptChunk
    + quarantine, at every verify depth including off, manifest or not."""
    for depth in ("size", "digest", "off"):
        shutil.rmtree(tmp_path / "quarantine", ignore_errors=True)
        np.save(chunk_path(tmp_path, 0), _data().astype(np.int8))
        with pytest.raises(integrity.CorruptChunk, match="no scale"):
            ChunkStore(tmp_path).load(0, verify=depth)


def test_fp16_overwrite_gap_detected(tmp_path, monkeypatch):
    """The save_chunk ordering bug (ISSUE 8 satellite): overwriting an int8
    chunk with fp16 used to unlink the scale file BEFORE the new bytes
    landed — a kill in the gap left old int8 bytes with no scale, silently
    loaded as raw integers. New ordering: the kill-in-the-gap state is new
    fp16 bytes + stale scale + old int8 manifest — detected and
    quarantined, never misread."""
    a = _data()
    save_chunk(tmp_path, 0, a, dtype=np.int8)
    monkeypatch.setenv(faults.FAULT_ENV, "torn_chunk_pair")
    faults.reset()
    with pytest.raises(faults.InjectedFault):
        save_chunk(tmp_path, 0, a)  # dies in the pair gap
    monkeypatch.delenv(faults.FAULT_ENV)
    faults.reset()
    # stale scale file still present next to the NEW fp16 bytes, old
    # manifest still describing the int8 pair
    assert scale_path(tmp_path, 0).exists()
    with pytest.raises(integrity.CorruptChunk):
        ChunkStore(tmp_path).load(0)
    assert integrity.quarantined_indices(tmp_path) == [0]
    # re-committing the chunk heals the slot
    save_chunk(tmp_path, 0, a)
    np.testing.assert_allclose(
        np.asarray(ChunkStore(tmp_path).load(0)), a, atol=2e-3 * np.abs(a).max()
    )


def test_torn_pair_never_observed_as_committed(tmp_path, monkeypatch):
    """A write killed before the manifest commit leaves an UNCOMMITTED
    chunk: fresh folders show the bytes but no manifest, and verification
    at any tier... passes legacy fp16 (bytes are self-consistent) — but a
    QUANTIZED torn pair is structurally detected. The stronger guarantee:
    overwrites are never half-applied (previous manifest keeps describing
    the previous bytes until the new commit)."""
    a = _data()
    save_chunk(tmp_path, 0, a, dtype=np.int8)
    before = integrity.read_chunk_manifest(tmp_path, 0)
    monkeypatch.setenv(faults.FAULT_ENV, "exc:chunk_write")
    faults.reset()
    with pytest.raises(faults.InjectedFault):
        save_chunk(tmp_path, 0, a * 2, dtype=np.int8)  # dies before anything lands
    monkeypatch.delenv(faults.FAULT_ENV)
    faults.reset()
    # nothing observable changed: old pair + old manifest still verify
    assert integrity.read_chunk_manifest(tmp_path, 0) == before
    assert integrity.verify_chunk(tmp_path, 0, depth="digest") == (True, "ok")
    np.testing.assert_allclose(
        np.asarray(ChunkStore(tmp_path).load(0)), a, atol=np.abs(a).max() / 120
    )


def test_corrupt_chunk_fault_action(tmp_path, monkeypatch):
    """`SC_FAULT=corrupt_chunk` flips a byte of the just-committed chunk —
    the bit-rot drill the digest tier must catch."""
    monkeypatch.setenv(faults.FAULT_ENV, "corrupt_chunk")
    faults.reset()
    save_chunk(tmp_path, 0, _data())
    ok, reason = integrity.verify_chunk(tmp_path, 0, depth="digest")
    assert not ok and "digest mismatch" in reason
    # size tier can't see it — exactly why scrub runs at digest
    assert integrity.verify_chunk(tmp_path, 0, depth="size") == (True, "ok")


def test_fault_grammar_new_actions():
    specs = faults.parse_faults("torn_chunk_pair;corrupt_chunk;kill:chunk_pair:chunk=2")
    assert [(s.action, s.site) for s in specs] == [
        ("torn_chunk_pair", "chunk_pair"),
        ("corrupt_chunk", "chunk_committed"),
        ("kill", "chunk_pair"),
    ]
    assert specs[0].max_fires == 1 and specs[1].max_fires == 1


# -- loss budget --------------------------------------------------------------

def test_loss_budget_accounting_and_exit_75(monkeypatch):
    telemetry = RunTelemetry(out_dir=None)
    try:
        budget = integrity.ChunkLossBudget(10, budget_frac=0.25, telemetry=telemetry)
        budget.skip(3, "digest mismatch", rows=100)
        budget.skip(3, "quarantined", rows=100)  # same chunk: one distinct loss
        budget.skip(7, "torn pair")
        assert budget.loss_frac == 0.2 and not budget.exceeded
        assert telemetry.counters["data.chunks_skipped"] == 3
        assert telemetry.counters["data.rows_skipped"] == 200
        with pytest.raises(SystemExit) as e:
            budget.skip(9, "digest mismatch")
        assert e.value.code == preemption.RESUMABLE_EXIT_CODE
        assert telemetry.counters["data.budget_exhausted"] == 1
    finally:
        telemetry.close()


def test_loss_budget_env_default(monkeypatch):
    assert integrity.default_loss_budget() == integrity.DEFAULT_LOSS_BUDGET
    monkeypatch.setenv(integrity.LOSS_BUDGET_ENV, "0.5")
    assert integrity.default_loss_budget() == 0.5


# -- driver degraded mode -----------------------------------------------------

def _synthetic_store(folder, n_chunks=3, rows=384, d=16, seed=0):
    gen = RandomDatasetGenerator(
        activation_dim=d, n_ground_truth_components=2 * d, batch_size=rows,
        feature_num_nonzero=5, feature_prob_decay=0.995, correlated=False,
        key=jax.random.PRNGKey(seed),
    )
    for i in range(n_chunks):
        save_chunk(folder, i, np.asarray(next(gen)))
    return ChunkStore(folder)


@pytest.mark.chaos
def test_basic_l1_sweep_degraded_mode(tmp_path, monkeypatch):
    """One truncated chunk inside the budget: the driver quarantines it,
    skips it with rows accounted, finishes — and the report + monitor
    render the loss."""
    from sparse_coding__tpu.telemetry.events import read_events
    from sparse_coding__tpu.telemetry.monitor import RunMonitor, render
    from sparse_coding__tpu.telemetry.report import load_run, render_markdown
    from sparse_coding__tpu.train.basic_l1_sweep import basic_l1_sweep

    store_dir = tmp_path / "chunks"
    _synthetic_store(store_dir, n_chunks=3)
    _truncate(chunk_path(store_dir, 1))
    monkeypatch.setenv(integrity.LOSS_BUDGET_ENV, "0.5")
    out = tmp_path / "out"
    dicts = basic_l1_sweep(
        str(store_dir), str(out), activation_width=16,
        l1_values=[1e-3], dict_ratio=2.0, batch_size=128, n_epochs=1,
        fista_iters=2, seed=0,
    )
    assert len(dicts) == 1  # run completed despite the loss
    assert integrity.quarantined_indices(store_dir) == [1]
    events = read_events(out / "events.jsonl")
    skips = [e for e in events if e.get("event") == "chunk_skipped"]
    assert len(skips) == 1 and skips[0]["chunk"] == 1 and skips[0]["rows"] == 384
    snap = [e for e in events if e.get("event") == "snapshot"][-1]
    assert snap["counters"]["data.corrupt"] == 1
    assert snap["counters"]["data.chunks_skipped"] == 1
    assert snap["counters"]["data.rows_skipped"] == 384
    assert snap["gauges"]["data.budget_remaining_frac"] > 0
    # only the two surviving chunks trained
    chunk_ends = [e for e in events if e.get("event") == "chunk_end"]
    assert len(chunk_ends) == 2
    md = render_markdown(load_run(out))
    assert "## Data integrity" in md
    assert "1 chunk(s) quarantined" in md
    assert "384 rows never trained" in md
    mon = RunMonitor(out)
    mon.poll()
    text = render(mon)
    assert "data: " in text and "1 quarantined" in text and "1 skipped" in text


@pytest.mark.chaos
def test_basic_l1_sweep_budget_exhaustion_exit_75(tmp_path, monkeypatch):
    """Past SC_CHUNK_LOSS_BUDGET the run raises ResumableAbort — SystemExit
    code 75, run_end recorded — never a raw traceback."""
    from sparse_coding__tpu.telemetry.events import read_events
    from sparse_coding__tpu.train.basic_l1_sweep import basic_l1_sweep

    store_dir = tmp_path / "chunks"
    _synthetic_store(store_dir, n_chunks=3)
    _truncate(chunk_path(store_dir, 0))
    monkeypatch.setenv(integrity.LOSS_BUDGET_ENV, "0.1")
    with pytest.raises(SystemExit) as e:
        basic_l1_sweep(
            str(store_dir), str(tmp_path / "out"), activation_width=16,
            l1_values=[1e-3], dict_ratio=2.0, batch_size=128, n_epochs=1,
            fista_iters=2, seed=0,
        )
    assert e.value.code == preemption.RESUMABLE_EXIT_CODE
    events = read_events(tmp_path / "out" / "events.jsonl")
    assert any(e.get("event") == "loss_budget_exhausted" for e in events)
    ends = [e for e in events if e.get("event") == "run_end"]
    assert ends and ends[-1]["status"].startswith("resumable-abort")


@pytest.mark.chaos
def test_sweep_degraded_mode(tmp_path, monkeypatch):
    """The sweep driver's prefetching iterator survives a corrupt chunk:
    stream rebuilt past the bad slot, loss accounted, run completes."""
    from test_sweep import l1_ensemble_init, make_cfg

    from sparse_coding__tpu.telemetry.events import read_events
    from sparse_coding__tpu.train import sweep

    cfg = make_cfg(tmp_path, n_epochs=1)
    # materialize the synthetic store first, then corrupt one chunk
    from sparse_coding__tpu.train.sweep import init_synthetic_dataset

    os.makedirs(cfg.output_folder, exist_ok=True)
    init_synthetic_dataset(cfg)
    _truncate(chunk_path(cfg.dataset_folder, 1))
    monkeypatch.setenv(integrity.LOSS_BUDGET_ENV, "0.5")
    dicts = sweep(l1_ensemble_init, cfg)
    assert len(dicts) == 2
    assert integrity.quarantined_indices(cfg.dataset_folder) == [1]
    events = read_events(Path(cfg.output_folder) / "events.jsonl")
    skips = [e for e in events if e.get("event") == "chunk_skipped"]
    assert [s["chunk"] for s in skips] == [1]
    assert len([e for e in events if e.get("event") == "chunk_end"]) == 2


@pytest.mark.chaos
def test_big_batch_store_input_degraded(tmp_path, monkeypatch):
    """`train_big_batch(dataset=<store folder>)` admits the store through
    the degraded-mode loader: corrupt chunk skipped within budget, training
    proceeds on the surviving rows."""
    from sparse_coding__tpu.models import FunctionalTiedSAE
    from sparse_coding__tpu.train.big_batch import train_big_batch

    store_dir = tmp_path / "chunks"
    _synthetic_store(store_dir, n_chunks=3, rows=256)
    _truncate(chunk_path(store_dir, 2))
    monkeypatch.setenv(integrity.LOSS_BUDGET_ENV, "0.5")
    telemetry = RunTelemetry(out_dir=None)
    try:
        state, sig = train_big_batch(
            FunctionalTiedSAE,
            {"activation_size": 16, "n_dict_components": 32, "l1_alpha": 1e-3},
            str(store_dir), batch_size=64, n_steps=3,
            key=jax.random.PRNGKey(0), reinit_every=None, telemetry=telemetry,
        )
        assert int(state.step) == 3
        assert telemetry.counters["data.chunks_skipped"] == 1
    finally:
        telemetry.close()


# -- scrub CLI + golden fixture -----------------------------------------------

def _copy_golden(tmp_path) -> Path:
    dst = tmp_path / "store"
    shutil.copytree(GOLDEN_STORE, dst)
    return dst


def test_scrub_cli_on_golden_corrupt_store(tmp_path, capsys):
    """The checked-in fixture pins the scrub CLI end to end: chunks 0-1
    verify, 2 (bit rot) / 3 (missing scale) / 4 (legacy torn) are
    quarantined, rendering and the exit-1 CI gate are stable."""
    from sparse_coding__tpu.data.scrub import main as scrub_main

    store = _copy_golden(tmp_path)
    rc = scrub_main([str(store), "--out", str(tmp_path / "scrub.md")])
    out = capsys.readouterr().out
    assert rc == 1  # unrepaired loss → CI gate trips
    assert integrity.quarantined_indices(store) == [2, 3, 4]
    assert ChunkStore(store).indices() == [0, 1]
    assert "Verified **2** chunk(s) at the `digest` tier" in out
    assert "**3 quarantined** this pass" in out
    assert "digest mismatch on 2.npy" in out
    assert "missing file 3.scale.npy" in out
    assert "no scale file" in out
    assert "UNREPAIRED LOSS" in out and "[2, 3, 4]" in out
    assert (tmp_path / "scrub.md").exists()
    # second pass: nothing new to quarantine, loss still reported
    rc2 = scrub_main([str(store)])
    assert rc2 == 1
    assert "**0 quarantined** this pass" in capsys.readouterr().out


def test_scrub_clean_store_exits_zero(tmp_path, capsys):
    save_chunk(tmp_path / "s", 0, _data())
    save_chunk(tmp_path / "s", 1, _data(seed=1), dtype=np.int8)
    from sparse_coding__tpu.data.scrub import main as scrub_main

    rc = scrub_main([str(tmp_path / "s")])
    assert rc == 0
    assert "store is whole" in capsys.readouterr().out


def test_scrub_repair_synthetic_store(tmp_path, capsys):
    """--repair regenerates exactly the quarantined indices through the
    seeded generator — bit-exact against an untouched control store."""
    from sparse_coding__tpu.data.chunks import generate_synthetic_chunks
    from sparse_coding__tpu.data.scrub import main as scrub_main

    gen_kwargs = dict(
        activation_dim=16, n_ground_truth_components=32, batch_size=256,
        feature_num_nonzero=5, feature_prob_decay=0.995, correlated=False,
    )
    spec = dict(
        n_chunks=3, chunk_size_gb=256 * 16 * 2 / 1024**3, activation_width=16,
    )
    for name in ("ctl", "vic"):
        gen = RandomDatasetGenerator(**gen_kwargs, key=jax.random.PRNGKey(3))
        generate_synthetic_chunks(gen, tmp_path / name, **spec)
    _bitflip(chunk_path(tmp_path / "vic", 1))
    config = {
        "kind": "synthetic",
        "generator": {**gen_kwargs, "class": "RandomDatasetGenerator", "seed": 3},
        **spec,
    }
    (tmp_path / "repair.json").write_text(json.dumps(config))
    rc = scrub_main([
        str(tmp_path / "vic"), "--repair", str(tmp_path / "repair.json"),
    ])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "1 repaired" in out
    for i in range(3):
        np.testing.assert_array_equal(
            chunk_path(tmp_path / "vic", i).read_bytes(),
            chunk_path(tmp_path / "ctl", i).read_bytes(),
        )


def test_scrub_detects_wholesale_tail_loss(tmp_path):
    """A partial copy that drops the TAIL chunks (files + manifests) must
    not look whole: the harvest cursor records how many chunks were
    committed, and scrub/store_loss use it as the expected-size floor."""
    import _harvest_worker as hw

    hw.harvest(tmp_path / "s")
    for i in (2, 3):  # the partial-rsync case: tail gone, manifests too
        chunk_path(tmp_path / "s", i).unlink()
        integrity.chunk_manifest_path(tmp_path / "s", i).unlink()
    summary = scrub_store(tmp_path / "s", depth="digest")
    assert summary["missing"] == [2, 3]
    loss = store_loss(tmp_path / "s", depth="digest")
    assert loss["bad"] == [2, 3] and loss["total"] == hw.N_CHUNKS


def test_store_loss_nonmutating(tmp_path):
    save_chunk(tmp_path, 0, _data())
    save_chunk(tmp_path, 1, _data(seed=1))
    _bitflip(chunk_path(tmp_path, 1))
    loss = store_loss(tmp_path, depth="digest")
    assert loss["bad"] == [1] and loss["total"] == 2 and loss["loss_frac"] == 0.5
    # nothing moved
    assert chunk_path(tmp_path, 1).exists()
    assert integrity.quarantined_indices(tmp_path) == []


# -- fleet admission check ----------------------------------------------------

@pytest.mark.chaos
def test_fleet_admission_requeues_input_corrupt(tmp_path, monkeypatch):
    """A claimed item whose chunk store is rotten beyond the loss budget is
    requeued with an `input_corrupt` lineage entry BEFORE any training —
    the input-side mirror of the scheduler's export_corrupt requeue."""
    from sparse_coding__tpu.fleet import FleetWorker, WorkQueue

    store_dir = tmp_path / "chunks"
    _synthetic_store(store_dir, n_chunks=2, rows=128)
    _bitflip(chunk_path(store_dir, 0))
    _bitflip(chunk_path(store_dir, 1))  # 100% loss ≫ any budget
    q = WorkQueue(tmp_path / "fleet")
    q.submit("g0", ["m0"], {
        "driver": "basic_l1_sweep",
        "kwargs": {"dataset_folder": str(store_dir), "activation_width": 16},
    })
    telemetry = RunTelemetry(out_dir=None)
    try:
        w = FleetWorker(tmp_path / "fleet", "w0", max_attempts=2,
                        telemetry=telemetry)
        assert w.claim_and_run() == "failed"
        (item,) = q.items("pending")
        assert item["attempt"] == 1
        assert item["lineage"][-1]["outcome"] == "input_corrupt"
        assert "corrupt beyond budget" in item["lineage"][-1]["error"]
        assert telemetry.counters["fleet.input_corrupt"] == 1
        # second claim burns the attempt budget → lost (failed bucket)
        assert w.claim_and_run() == "failed"
        assert [i["item"] for i in q.items("failed")] == ["g0"]
        # admission is non-mutating: the store itself was not quarantined
        assert integrity.quarantined_indices(store_dir) == []
    finally:
        telemetry.close()


def test_fleet_admission_passes_within_budget(tmp_path, monkeypatch):
    """Loss inside the budget admits the item — degraded-mode training is
    the driver's job, not a reason to bounce work around the fleet."""
    from sparse_coding__tpu.fleet import FleetWorker, WorkQueue

    store_dir = tmp_path / "chunks"
    _synthetic_store(store_dir, n_chunks=3, rows=128)
    _bitflip(chunk_path(store_dir, 2))
    monkeypatch.setenv(integrity.LOSS_BUDGET_ENV, "0.5")
    monkeypatch.setenv(integrity.CHUNK_VERIFY_ENV, "digest")
    q = WorkQueue(tmp_path / "fleet")
    q.submit("g0", ["m0"], {
        "driver": "basic_l1_sweep",
        "kwargs": {
            "dataset_folder": str(store_dir), "activation_width": 16,
            "l1_values": [1e-3], "dict_ratio": 2.0, "batch_size": 64,
            "n_epochs": 1, "fista_iters": 2,
        },
    })
    w = FleetWorker(tmp_path / "fleet", "w0")
    assert w.claim_and_run() == "done"
    # the driver quarantined + skipped the rotten chunk in degraded mode
    assert integrity.quarantined_indices(store_dir) == [2]


# -- harvest: cursor resume, verified skip, store_dtype -----------------------

def test_harvest_cursor_resume_matches_full(tmp_path):
    """A harvest stopped after 2 chunks resumes from its committed cursor
    and produces a store byte-identical to an uninterrupted one."""
    import _harvest_worker as hw

    hw.harvest(tmp_path / "full")
    cfg, params, tokens = hw.build_subject()
    from sparse_coding__tpu.data.activations import make_activation_dataset

    chunk_gb = hw.BATCH * hw.SEQ * cfg.d_model * 2 / 1024**3
    kw = dict(
        layers=[1], layer_locs=["residual"], batch_size=hw.BATCH,
        chunk_size_gb=chunk_gb, single_folder=True,
    )
    make_activation_dataset(params, cfg, tokens, tmp_path / "part",
                            n_chunks=2, **kw)
    cursor = json.loads((tmp_path / "part" / "sc_harvest_cursor.json").read_text())
    assert cursor["chunk"] == 2
    make_activation_dataset(params, cfg, tokens, tmp_path / "part",
                            n_chunks=hw.N_CHUNKS, resume=True, **kw)
    for i in range(hw.N_CHUNKS):
        assert chunk_path(tmp_path / "part", i).read_bytes() == \
            chunk_path(tmp_path / "full", i).read_bytes()


def test_harvest_resume_reharvests_unverified(tmp_path):
    """A torn chunk under the cursor truncates the resume point — the bad
    chunk is re-harvested instead of trusted (the old skip_chunks trusted
    bare file existence)."""
    import _harvest_worker as hw

    hw.harvest(tmp_path / "s")
    # tear chunk 1: bytes truncated after commit
    _truncate(chunk_path(tmp_path / "s", 1))
    with pytest.warns(RuntimeWarning, match="re-harvesting from chunk 1"):
        hw.harvest(tmp_path / "s", resume=True)
    hw.harvest(tmp_path / "ctl")
    for i in range(hw.N_CHUNKS):
        assert chunk_path(tmp_path / "s", i).read_bytes() == \
            chunk_path(tmp_path / "ctl", i).read_bytes()


def test_harvest_resume_config_mismatch_refused(tmp_path):
    import _harvest_worker as hw

    cfg, params, tokens = hw.build_subject()
    from sparse_coding__tpu.data.activations import make_activation_dataset

    chunk_gb = hw.BATCH * hw.SEQ * cfg.d_model * 2 / 1024**3
    make_activation_dataset(
        params, cfg, tokens, tmp_path / "s", layers=[1],
        layer_locs=["residual"], batch_size=hw.BATCH, chunk_size_gb=chunk_gb,
        n_chunks=2, single_folder=True,
    )
    with pytest.raises(ValueError, match="different configuration"):
        make_activation_dataset(
            params, cfg, tokens, tmp_path / "s", layers=[1],
            layer_locs=["residual"], batch_size=hw.BATCH // 2,
            chunk_size_gb=chunk_gb, n_chunks=2, single_folder=True,
            resume=True,
        )


def test_harvest_to_device_store_dtype(tmp_path):
    """The fused harvest's save_folder can persist quantized tiers now
    (ISSUE 8 satellite) — int8 store with scale side files + manifests."""
    import _harvest_worker as hw

    from sparse_coding__tpu.data.activations import harvest_to_device

    cfg, params, tokens = hw.build_subject()
    chunk_gb = hw.BATCH * hw.SEQ * cfg.d_model * 2 / 1024**3
    chunks = list(harvest_to_device(
        params, cfg, tokens, layers=[1], layer_locs=["residual"],
        batch_size=hw.BATCH, chunk_size_gb=chunk_gb, n_chunks=2,
        save_folder=tmp_path / "dev", store_dtype=np.int8,
    ))
    assert len(chunks) == 2
    from sparse_coding__tpu.data.activations import harvest_folder_name

    folder = harvest_folder_name(tmp_path / "dev", 1, "residual")
    assert scale_path(folder, 0).exists()
    m = integrity.read_chunk_manifest(folder, 0)
    assert m["store_dtype"] == "int8"
    # the persisted quantized chunk dequantizes to ~the yielded fp16 values
    dev = np.asarray(jax.device_get(chunks[0][(1, "residual")])).astype(np.float32)
    disk = np.asarray(ChunkStore(folder).load(0))
    atol = float((np.abs(dev).max(axis=1) / 100).max() + 1e-4)
    np.testing.assert_allclose(disk, dev, atol=atol)


# -- chaos acceptance ---------------------------------------------------------

def _worker_env():
    env = dict(os.environ)
    env["PYTHONPATH"] = str(REPO) + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    # match the in-process test environment exactly — the acceptance
    # compares chunk BYTES across the process boundary
    env["XLA_FLAGS"] = os.environ.get("XLA_FLAGS", "")
    env.pop("SC_FAULT", None)
    env.pop("SC_RESUME", None)
    return env


@pytest.mark.chaos
def test_chaos_harvest_kill_scrub_repair_train(tmp_path, monkeypatch):
    """The ISSUE 8 acceptance drill end to end:

    1. harvest SIGKILLed mid-chunk-pair (`SC_FAULT=kill:chunk_pair:chunk=2`,
       a REAL SIGKILL in a subprocess) → chunk 2 left uncommitted;
    2. resumed harvest restarts from the last committed chunk and finishes;
    3. one chunk bit-flipped post-hoc → scrub quarantines exactly it;
    4. `only_chunks` repair refills the hole; the store is then bit-exact
       vs an uninterrupted control harvest;
    5. training over the repaired store is bit-exact vs the control;
    6. a degraded-mode run over the UNREPAIRED store finishes inside
       `SC_CHUNK_LOSS_BUDGET` with the skipped rows accounted.
    """
    import _harvest_worker as hw

    from sparse_coding__tpu.telemetry.events import read_events
    from sparse_coding__tpu.train.basic_l1_sweep import basic_l1_sweep
    from sparse_coding__tpu.train.checkpoint import load_learned_dicts

    ctl = tmp_path / "ctl"
    vic = tmp_path / "vic"
    hw.harvest(ctl)  # uninterrupted control, in-process

    # 1: SIGKILL mid-pair — must be a subprocess (SIGKILL takes no prisoners)
    env = _worker_env()
    env["SC_FAULT"] = "kill:chunk_pair:chunk=2"
    res = subprocess.run(
        [sys.executable, str(REPO / "tests" / "_harvest_worker.py"), str(vic)],
        env=env, capture_output=True, text=True, timeout=300,
    )
    assert res.returncode == -9, (res.returncode, res.stderr[-500:])
    # chunk 2's pair gap: bytes may exist, but it is NOT committed
    assert integrity.read_chunk_manifest(vic, 2) is None
    assert integrity.read_chunk_manifest(vic, 1) is not None

    # 2: resume from the last committed chunk (in-process, same seeds) —
    # the cursor says 2, so the torn chunk-2 bytes are simply re-harvested
    hw.harvest(vic, resume=True)
    for i in range(hw.N_CHUNKS):
        assert chunk_path(vic, i).read_bytes() == chunk_path(ctl, i).read_bytes(), i

    # 3: post-hoc bit rot in chunk 1 → scrub (digest tier) quarantines it
    _bitflip(chunk_path(vic, 1))
    degraded = tmp_path / "degraded"
    shutil.copytree(vic, degraded)  # keep an unrepaired copy for step 6
    summary = scrub_store(vic, depth="digest")
    assert [f["chunk"] for f in summary["failed"]] == [1]
    assert summary["missing"] == [1]
    assert integrity.quarantined_indices(vic) == [1]
    md = render_scrub_markdown(summary)
    assert "UNREPAIRED LOSS" in md

    # 4: repair exactly the hole; bit-exact vs control
    hw.harvest(vic, only_chunks=[1])
    assert scrub_store(vic, depth="digest")["missing"] == []
    for i in range(hw.N_CHUNKS):
        assert chunk_path(vic, i).read_bytes() == chunk_path(ctl, i).read_bytes(), i

    # 5: training over the repaired store == training over the control
    kw = dict(activation_width=16, l1_values=[1e-3], dict_ratio=2.0,
              batch_size=64, n_epochs=1, fista_iters=2, seed=0)
    basic_l1_sweep(str(ctl), str(tmp_path / "t_ctl"), **kw)
    basic_l1_sweep(str(vic), str(tmp_path / "t_vic"), **kw)
    d_ctl = load_learned_dicts(tmp_path / "t_ctl" / "epoch_0" / "learned_dicts.pkl")
    d_vic = load_learned_dicts(tmp_path / "t_vic" / "epoch_0" / "learned_dicts.pkl")
    np.testing.assert_array_equal(
        np.asarray(d_ctl[0][0].get_learned_dict()),
        np.asarray(d_vic[0][0].get_learned_dict()),
    )

    # 6: degraded mode over the UNREPAIRED copy — finishes inside budget,
    # loss accounted in telemetry (digest tier: the rot is a bit flip, the
    # size tier can't see it — this is what SC_CHUNK_VERIFY exists for)
    monkeypatch.setenv(integrity.CHUNK_VERIFY_ENV, "digest")
    monkeypatch.setenv(integrity.LOSS_BUDGET_ENV, "0.3")
    basic_l1_sweep(str(degraded), str(tmp_path / "t_deg"), **kw)
    assert integrity.quarantined_indices(degraded) == [1]
    events = read_events(tmp_path / "t_deg" / "events.jsonl")
    skips = [e for e in events if e.get("event") == "chunk_skipped"]
    assert [s["chunk"] for s in skips] == [1]
    snap = [e for e in events if e.get("event") == "snapshot"][-1]
    assert snap["counters"]["data.chunks_skipped"] == 1
    assert len([e for e in events if e.get("event") == "chunk_end"]) == hw.N_CHUNKS - 1
