"""Train loop: FISTA decoder update wiring + buffered logging."""

import json

import jax
import jax.numpy as jnp
import numpy as np

from sparse_coding__tpu.ensemble import build_ensemble
from sparse_coding__tpu.models import FunctionalFista, FunctionalTiedSAE
from sparse_coding__tpu.train import ensemble_train_loop
from sparse_coding__tpu.utils import MetricLogger, make_hyperparam_name


def _planted(key, n=32, d=16, rows=512):
    k_d, k_c, k_m = jax.random.split(key, 3)
    D = jax.random.normal(k_d, (n, d))
    D = D / jnp.linalg.norm(D, axis=-1, keepdims=True)
    codes = jax.random.uniform(k_c, (rows, n)) * jax.random.bernoulli(k_m, 0.15, (rows, n))
    return D, codes @ D


def test_fista_loop_updates_decoder_and_hessian(tmp_path):
    D, data = _planted(jax.random.PRNGKey(0))
    ens = build_ensemble(
        FunctionalFista,
        jax.random.PRNGKey(1),
        [{"l1_alpha": 1e-4}, {"l1_alpha": 1e-3}],
        optimizer_kwargs={"learning_rate": 1e-3},
        activation_size=16,
        n_dict_components=32,
    )
    dec_before = np.asarray(jax.device_get(ens.state.params["decoder"]))
    hess_before = np.asarray(jax.device_get(ens.state.buffers["hessian_diag"]))
    assert (hess_before == 0).all()

    logger = MetricLogger(out_dir=str(tmp_path), run_name="fista_test")
    loss = ensemble_train_loop(
        ens, data, batch_size=64, key=jax.random.PRNGKey(2),
        logger=logger, log_every=4, fista_iters=50,
    )
    logger.close()

    dec_after = jax.device_get(ens.state.params["decoder"])
    hess_after = jax.device_get(ens.state.buffers["hessian_diag"])
    assert not np.allclose(dec_before, dec_after), "FISTA update never touched decoder"
    assert (np.asarray(hess_after) > 0).any(), "hessian EMA did not persist"
    # FISTA basis update keeps decoder rows unit-norm
    norms = np.linalg.norm(np.asarray(dec_after), axis=-1)
    assert np.allclose(norms, 1.0, atol=1e-5)
    assert np.isfinite(jax.device_get(loss["loss"])).all()

    # JSONL logging wrote per-model series without per-step host syncs
    records = [json.loads(l) for l in open(tmp_path / "fista_test_metrics.jsonl")]
    assert {r["series"] for r in records} == {"model_0", "model_1"}
    assert {r["metric"] for r in records} >= {"loss", "l_reconstruction", "l_l1"}


def test_loop_skips_fista_for_tied_sae():
    """Signatures without a decoder must not hit the FISTA path (the reference
    crashes here, big_sweep.py:180-198 / SURVEY.md §2.7)."""
    _, data = _planted(jax.random.PRNGKey(3))
    ens = build_ensemble(
        FunctionalTiedSAE,
        jax.random.PRNGKey(4),
        [{"l1_alpha": 1e-3}],
        optimizer_kwargs={"learning_rate": 1e-3},
        activation_size=16,
        n_dict_components=32,
    )
    loss = ensemble_train_loop(ens, data, batch_size=64, key=jax.random.PRNGKey(5))
    assert np.isfinite(jax.device_get(loss["loss"])).all()


def test_fista_decoder_update_is_cached():
    """Repeated loop calls must reuse one jitted update object — no re-trace
    of the 500-iteration solve per chunk (round-1 VERDICT weak #3)."""
    from sparse_coding__tpu.train.loop import make_fista_decoder_update

    a = make_fista_decoder_update(50, use_pallas=False)
    b = make_fista_decoder_update(50, use_pallas=False)
    assert a is b
    assert make_fista_decoder_update(51, use_pallas=False) is not a


def test_make_hyperparam_name():
    # reference format: {:.2E} with "+" stripped (big_sweep.py:76-84)
    assert make_hyperparam_name({"l1_alpha": 1e-3}) == "l1_alpha_1.00E-03"
    assert make_hyperparam_name({"k": 4, "l1_alpha": 1e-2}) == "k_4_l1_alpha_1.00E-02"


def test_step_timer_and_trace(tmp_path):
    import warnings

    from sparse_coding__tpu.utils import StepTimer, trace, annotate
    from sparse_coding__tpu.utils.trace import trace_active
    import jax.numpy as jnp
    import pytest

    t = StepTimer()
    x = jnp.zeros((4,))
    for _ in range(3):
        x = x + 1
        t.tick()
    rep = t.report(fence=x)
    # ticks count as steps; the fence only extends total time (trace.py:60-65)
    assert rep["steps"] == 3 and rep["total_s"] >= 0
    # dispatch stats are host-side (up to the last tick): the fence can only
    # extend the fenced window, so dispatch rate >= fenced rate
    assert rep["dispatch_steps_per_sec"] >= rep["steps_per_sec"] > 0
    assert rep["dispatch_mean_step_ms"] <= rep["mean_step_ms"]

    with trace(str(tmp_path / "trace")):
        assert trace_active() == str(tmp_path / "trace")
        with annotate("toy"):
            jax.device_get(jnp.ones((8,)) * 2)
        # reentrancy: a nested trace must degrade to a warning, not raise
        # from jax.profiler.start_trace and kill the outer trace
        with pytest.warns(RuntimeWarning, match="already active"):
            with trace(str(tmp_path / "nested")) as d:
                jax.device_get(jnp.ones((4,)) + 1)
        assert trace_active() == str(tmp_path / "trace"), "outer trace died"
    assert trace_active() is None
    assert any((tmp_path / "trace").rglob("*")), "no trace files written"
    # the nested block must not have stopped the profiler for the outer one
    # (stop after the outer exit is a safe no-op)
    from sparse_coding__tpu.utils.trace import stop_trace_safe

    with warnings.catch_warnings():
        warnings.simplefilter("error")
        assert stop_trace_safe() is None


def test_log_image_wandb_path(tmp_path, monkeypatch):
    """With wandb live, images go through wandb.log WITHOUT an explicit step
    (scalar logging advances the run step per batch; a smaller explicit step
    would be dropped by wandb's monotonic rule) and carry the chunk index as
    a sibling metric. Stubbed wandb — no network."""
    import sys
    import types

    import matplotlib.pyplot as plt

    calls = []
    stub = types.ModuleType("wandb")
    stub.Image = lambda fig: ("IMG", fig)
    stub.init = lambda **kw: types.SimpleNamespace(
        log=lambda payload, **kw2: calls.append((payload, kw2)),
        finish=lambda: None,
    )
    monkeypatch.setitem(sys.modules, "wandb", stub)

    logger = MetricLogger(out_dir=str(tmp_path), run_name="t", use_wandb=True)
    fig = plt.figure()
    try:
        assert logger.log_image(7, "mmcs_grid", fig) is None
    finally:
        plt.close(fig)
    (payload, kwargs), = [c for c in calls if "mmcs_grid" in c[0]]
    assert payload["mmcs_grid"][0] == "IMG"
    assert payload["mmcs_grid_chunk"] == 7
    assert "step" not in kwargs  # no monotonic-step violation
    # file fallback NOT used when wandb is live
    assert not (tmp_path / "images").exists()
