"""Chunk-store integrity: per-chunk commit manifests, verify tiers, quarantine,
and the degraded-mode loss budget.

The activation chunk store is the framework's only data contract
(`data/chunks.py`, reference `activation_dataset.py:393-397`) — and until
this layer it was trust-based: `save_chunk` wrote `.npy` files
non-atomically, so a kill between a quantized chunk and its `{i}.scale.npy`
side file left raw int8 bytes that `ChunkStore.load` silently fed to
training as activations. This module gives the data plane the same
commit-verify-recover treatment the checkpoint layer got in PR 5
(`train.checkpoint`):

**Commit.** `save_chunk` stages chunk + scale in dot-prefixed temps and
lands them with a final `os.replace` of a per-chunk manifest
(``sc_chunk.<i>.json``: per-file byte sizes + sha256, rows, shape, store
dtype/quant tier, harvest provenance) — the ONE atomic commit point. A
chunk without a matching manifest is uncommitted by definition; a torn
pair can never be observed as data.

**Verify.** `verify_chunk` checks a chunk against its manifest at a depth
set by ``SC_CHUNK_VERIFY``:

    size   (default) existence + byte sizes — catches torn pairs,
           truncation, and format flips (int8 bytes under an fp16
           manifest); cheap enough for every hot-loop load
    digest sizes + sha256 of every file — catches bit rot; the scrub CLI
           and fleet admission checks run at this depth
    off    skip manifest verification (structural missing-scale detection
           in `ChunkStore.load` still applies — silent misreads stay
           impossible at every depth)

Manifest-less chunks are *legacy* (pre-manifest stores): verification
passes them except for the one structurally detectable corruption —
quantized bytes with no scale file.

**Quarantine + degraded mode.** A chunk that fails verification is moved
into ``<store>/quarantine/`` (never deleted — an operator can inspect or
restore it), a ``data.corrupt`` counter and an anomaly-style
``chunk_corrupt`` event land on any live telemetry, and the load raises
`CorruptChunk`. Drivers catch it and consult a `ChunkLossBudget`
(``SC_CHUNK_LOSS_BUDGET``, default 5% of distinct chunks): inside the
budget the chunk is skipped and accounted (``data.chunks_skipped`` /
``data.rows_skipped``); past it the budget raises
`train.preemption.ResumableAbort` — exit 75, never a raw traceback, never
silent corruption.

Repair: ``python -m sparse_coding__tpu.data.scrub <store>`` (see
`data.scrub`) verifies a whole store, quarantines failures, and
re-harvests missing indices. docs/DATAPLANE.md has the failure matrix.
"""

from __future__ import annotations

import hashlib
import json
import os
import time
from pathlib import Path
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from sparse_coding__tpu.utils import flags

__all__ = [
    "CHUNK_VERIFY_ENV",
    "LOSS_BUDGET_ENV",
    "QUARANTINE_DIR",
    "ChunkLossBudget",
    "CorruptChunk",
    "chunk_manifest_path",
    "default_loss_budget",
    "is_quarantined",
    "npy_header",
    "quarantine_chunk",
    "quarantined_indices",
    "quarantined_rows",
    "read_chunk_manifest",
    "verify_chunk",
    "verify_depth",
    "write_chunk_manifest",
    "write_json_atomic",
]

# verification depth for chunk loads: size (default) | digest | off.
# Unlike SC_CKPT_VERIFY (default digest — resume is rare), chunk loads are
# the hot loop: a digest re-read of every chunk every epoch is real I/O, so
# the default is the size tier and digest is reserved for scrub / admission.
CHUNK_VERIFY_ENV = flags.SC_CHUNK_VERIFY.name

# degraded-mode budget: the fraction of DISTINCT chunks a run may lose to
# quarantine before it stops trusting the dataset and exits resumable (75)
LOSS_BUDGET_ENV = flags.SC_CHUNK_LOSS_BUDGET.name
DEFAULT_LOSS_BUDGET = 0.05

QUARANTINE_DIR = "quarantine"

_QUANT_DTYPES = ("int8", "uint8")  # on-disk dtypes that REQUIRE a scale file


class CorruptChunk(RuntimeError):
    """A chunk that failed integrity verification (torn pair, missing scale,
    size/digest mismatch, unreadable bytes) — already quarantined by the
    raiser. Drivers route this into degraded-mode skip-and-account, NEVER
    into training data."""

    def __init__(self, store, chunk: int, reason: str):
        super().__init__(f"chunk {chunk} of {store} is corrupt: {reason}")
        self.store = str(store)
        self.chunk = int(chunk)
        self.reason = reason


def chunk_manifest_path(folder, i: int) -> Path:
    return Path(folder) / f"sc_chunk.{int(i)}.json"


def verify_depth(depth: Optional[str] = None) -> str:
    """Resolve a verification depth: explicit arg > SC_CHUNK_VERIFY > size."""
    d = (depth or flags.SC_CHUNK_VERIFY.get()).lower()
    if d not in ("digest", "size", "off"):
        raise ValueError(
            f"unknown {CHUNK_VERIFY_ENV} depth {d!r} (digest | size | off)"
        )
    return d


def default_loss_budget() -> float:
    """The degraded-mode loss budget fraction (SC_CHUNK_LOSS_BUDGET)."""
    raw = flags.SC_CHUNK_LOSS_BUDGET.raw()
    if raw is None or raw == "":
        return DEFAULT_LOSS_BUDGET
    return float(raw)


def _sha256_file(path: Path) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for block in iter(lambda: f.read(1 << 20), b""):
            h.update(block)
    return h.hexdigest()


def write_json_atomic(path: Path, obj: Dict[str, Any]) -> Path:
    """Same-dir temp + `os.replace` — the commit idiom every durable write
    in this repo uses (a kill mid-write leaves the previous complete file or
    nothing, never a torn one)."""
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    tmp = path.with_name(f".{path.name}.tmp{os.getpid()}")
    with open(tmp, "w") as f:
        json.dump(obj, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return path


def write_chunk_manifest(
    folder,
    i: int,
    files: Dict[str, Path],
    rows: int,
    shape,
    store_dtype: str,
    provenance: Optional[Dict[str, Any]] = None,
) -> Path:
    """Commit chunk `i`: hash the already-landed data files and `os.replace`
    the manifest onto its final name — the single atomic commit point of the
    chunk-pair write protocol (`data.chunks.save_chunk`).

    Digests are ALWAYS recorded — the chunk bytes were just written, so the
    hashing re-read is served from page cache, and a manifest without
    digests would make the scrub/admission digest tier silently toothless
    for the store's whole lifetime. ``SC_CHUNK_VERIFY`` tunes READ-side
    verification only; it must never degrade what future readers can
    check."""
    entries: Dict[str, Dict[str, Any]] = {}
    for name, p in files.items():
        p = Path(p)
        entries[name] = {
            "bytes": p.stat().st_size,
            "sha256": _sha256_file(p),
        }
    manifest = {
        "format": 1,
        "chunk": int(i),
        "created_at": time.time(),
        "rows": int(rows),
        "shape": [int(s) for s in shape],
        "store_dtype": str(store_dtype),
        "files": entries,
    }
    if provenance:
        manifest["provenance"] = provenance
    return write_json_atomic(chunk_manifest_path(folder, i), manifest)


def read_chunk_manifest(folder, i: int) -> Optional[Dict[str, Any]]:
    """Chunk `i`'s commit manifest, or None when uncommitted/unreadable."""
    try:
        with open(chunk_manifest_path(folder, i)) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return None


def npy_header(path: Path):
    """(shape, dtype) from a .npy header via the PUBLIC numpy format API —
    the private `_read_array_header` breaks across numpy versions."""
    with open(path, "rb") as f:
        version = np.lib.format.read_magic(f)
        if version == (1, 0):
            shape, _, dtype = np.lib.format.read_array_header_1_0(f)
        else:
            # 2.0 and 3.0 share the header layout; 3.0 only changes the
            # allowed field-name encoding
            shape, _, dtype = np.lib.format.read_array_header_2_0(f)
    return shape, dtype


def verify_chunk(folder, i: int, depth: Optional[str] = None) -> Tuple[bool, str]:
    """Is chunk `i` committed and intact at `depth`? Returns (ok, reason).

    Manifest present → every listed file must exist with matching byte size
    (and sha256 at the digest tier). Manifest absent → a legacy chunk:
    passes unless it is structurally corrupt (quantized on-disk bytes with
    no scale file — the torn pair the pre-manifest format could not
    detect). A missing chunk file fails either way."""
    from sparse_coding__tpu.data.chunks import chunk_path, scale_path

    folder = Path(folder)
    depth = verify_depth(depth)
    cp = chunk_path(folder, i)
    manifest = read_chunk_manifest(folder, i)
    if manifest is None:
        if not cp.is_file():
            return False, "missing chunk file"
        if depth == "off":
            return True, "ok (verification off)"
        try:
            _, dtype = npy_header(cp)
        except (OSError, ValueError) as e:
            return False, f"unreadable npy header: {e}"
        if dtype.name in _QUANT_DTYPES and not scale_path(folder, i).is_file():
            return False, (
                f"quantized ({dtype.name}) chunk bytes with no scale file — "
                "torn pair (legacy, no manifest)"
            )
        return True, "ok (legacy, no manifest)"
    if depth == "off":
        return True, "ok (verification off)"
    for rel, meta in manifest.get("files", {}).items():
        p = folder / rel
        if not p.is_file():
            return False, f"missing file {rel}"
        if p.stat().st_size != meta.get("bytes"):
            return False, f"size mismatch on {rel}"
        if depth == "digest" and "sha256" in meta and _sha256_file(p) != meta["sha256"]:
            return False, f"digest mismatch on {rel}"
    # files not in the manifest that change the load's interpretation: a
    # stale scale file next to a committed fp16 chunk would flip the loader
    # into dequantizing real fp16 bytes
    sp = scale_path(folder, i)
    if sp.is_file() and sp.name not in manifest.get("files", {}):
        return False, f"stray scale file {sp.name} not in manifest"
    return True, "ok"


def _quarantine_root(folder) -> Path:
    return Path(folder) / QUARANTINE_DIR


def is_quarantined(folder, i: int) -> bool:
    q = _quarantine_root(folder)
    return (q / f"{int(i)}.npy").exists() or (q / f"sc_quarantine.{int(i)}.json").exists()


def quarantined_indices(folder) -> List[int]:
    q = _quarantine_root(folder)
    if not q.is_dir():
        return []
    idx = set()
    for p in q.iterdir():
        if p.suffix == ".npy" and p.stem.isdigit():
            idx.add(int(p.stem))
        elif p.name.startswith("sc_quarantine.") and p.suffix == ".json":
            mid = p.name[len("sc_quarantine."):-len(".json")]
            if mid.isdigit():
                idx.add(int(mid))
    return sorted(idx)


def quarantined_rows(folder, i: int) -> Optional[int]:
    """Row count of a quarantined chunk (manifest first, npy header second)
    — so degraded-mode epoch accounting knows how much data went missing.
    None when it cannot be determined (e.g. truncated bytes)."""
    q = _quarantine_root(folder)
    try:
        with open(q / f"sc_chunk.{int(i)}.json") as f:
            manifest = json.load(f)
        if isinstance(manifest.get("rows"), int):
            return manifest["rows"]
    except (OSError, json.JSONDecodeError):
        pass
    try:
        shape, _ = npy_header(q / f"{int(i)}.npy")
        return int(shape[0])
    except (OSError, ValueError, IndexError):
        return None


def quarantine_chunk(folder, i: int, reason: str) -> List[Path]:
    """Move chunk `i`'s files (data, scale, manifest) into
    ``<store>/quarantine/`` and record the reason — detection must never
    destroy the evidence. Bumps the ``data.corrupt`` counter and emits an
    anomaly-style ``chunk_corrupt`` event on any live telemetry. Returns the
    moved paths. Idempotent: already-moved files are skipped."""
    from sparse_coding__tpu.data.chunks import chunk_path, scale_path
    from sparse_coding__tpu.telemetry.events import counter_inc_active, event_active

    folder = Path(folder)
    q = _quarantine_root(folder)
    q.mkdir(parents=True, exist_ok=True)
    moved: List[Path] = []
    for p in (chunk_path(folder, i), scale_path(folder, i), chunk_manifest_path(folder, i)):
        if p.is_file():
            dst = q / p.name
            os.replace(p, dst)
            moved.append(dst)
    write_json_atomic(
        q / f"sc_quarantine.{int(i)}.json",
        {"chunk": int(i), "reason": reason, "quarantined_at": time.time(),
         "files": [p.name for p in moved]},
    )
    counter_inc_active("data.corrupt")
    event_active(
        "anomaly", kind="chunk_corrupt", action="quarantine",
        chunk=int(i), reason=reason, store=str(folder),
    )
    return moved


class ChunkLossBudget:
    """Degraded-mode accounting: how much of the dataset a run may lose.

    Drivers construct one per run and call `skip(chunk, reason, rows=...)`
    for every `CorruptChunk` they survive. Skips are counted in DISTINCT
    chunk indices (an epoch loop re-skipping the same quarantined chunk is
    one loss, not n_epochs losses); rows are accumulated separately so
    epoch accounting can correct for what training never saw. When the
    distinct-loss fraction exceeds the budget (``SC_CHUNK_LOSS_BUDGET``,
    default 5%), `skip` raises `train.preemption.ResumableAbort` — exit 75,
    the same resumable contract as a preemption or an exhausted read retry,
    so the supervisor/fleet can repair (scrub + re-harvest) and retry
    instead of a human reading a traceback."""

    def __init__(
        self,
        n_chunks: int,
        budget_frac: Optional[float] = None,
        telemetry=None,
    ):
        self.n_chunks = max(1, int(n_chunks))
        self.budget_frac = (
            default_loss_budget() if budget_frac is None else float(budget_frac)
        )
        self.telemetry = telemetry
        self.skipped_chunks: set = set()
        self.rows_skipped = 0
        self._events = 0
        self._gauge(self.budget_frac)

    # telemetry plumbing: prefer the driver's handle; fall back to the
    # process-global fan-out so library callers still account
    def _counter(self, name: str, n=1):
        from sparse_coding__tpu.telemetry.events import counter_inc_active

        if self.telemetry is not None:
            self.telemetry.counter_inc(name, n)
        else:
            counter_inc_active(name, n)

    def _gauge(self, remaining: float):
        from sparse_coding__tpu.telemetry.events import gauge_set_active

        if self.telemetry is not None:
            self.telemetry.gauge_set("data.budget_remaining_frac", remaining)
        else:
            gauge_set_active("data.budget_remaining_frac", remaining)

    def _event(self, etype: str, **fields):
        from sparse_coding__tpu.telemetry.events import event_active

        if self.telemetry is not None:
            self.telemetry.event(etype, **fields)
        else:
            event_active(etype, **fields)

    @property
    def loss_frac(self) -> float:
        return len(self.skipped_chunks) / self.n_chunks

    @property
    def remaining_frac(self) -> float:
        return max(0.0, self.budget_frac - self.loss_frac)

    @property
    def exceeded(self) -> bool:
        return self.loss_frac > self.budget_frac

    def skip(self, chunk: int, reason: str, rows: Optional[int] = None) -> None:
        """Account one skipped chunk; raise `ResumableAbort` past budget."""
        self.skipped_chunks.add(int(chunk))
        self._events += 1
        if rows:
            self.rows_skipped += int(rows)
            self._counter("data.rows_skipped", int(rows))
        self._counter("data.chunks_skipped")
        self._gauge(self.remaining_frac)
        self._event(
            "chunk_skipped", chunk=int(chunk), reason=reason,
            rows=rows, loss_frac=round(self.loss_frac, 4),
            budget_frac=self.budget_frac,
        )
        if self.exceeded:
            from sparse_coding__tpu.train.preemption import ResumableAbort

            self._counter("data.budget_exhausted")
            self._event(
                "loss_budget_exhausted",
                chunks_lost=sorted(self.skipped_chunks),
                loss_frac=round(self.loss_frac, 4),
                budget_frac=self.budget_frac,
            )
            raise ResumableAbort(
                f"chunk loss budget exhausted: {len(self.skipped_chunks)}/"
                f"{self.n_chunks} chunks lost "
                f"({self.loss_frac:.1%} > {self.budget_frac:.1%} "
                f"{LOSS_BUDGET_ENV}); scrub/repair the store and resume"
            )
