"""Elastic resume walkthrough: train sharded on one mesh shape, checkpoint,
restore on a DIFFERENT shape (here: a preemption that came back with half the
devices), continue bit-compatibly.

Run: `python examples/elastic_resume_example.py` (uses 8 virtual CPU devices
if no multi-device backend is attached).
"""

import os
import sys
import tempfile
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

if os.environ.get("_ELASTIC_EXAMPLE_CPU") == "1":
    # second exec: virtual 8-device CPU backend (the multi-chip dry-run
    # trick). Env vars alone are not honored on every backend plugin, so
    # force the platform through jax.config before any backend init.
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    import jax

    jax.config.update("jax_platforms", "cpu")
else:
    import jax

    if len(jax.devices()) < 8:
        # attached backend too small for the (2,2,2) mesh: re-exec virtual
        os.environ["_ELASTIC_EXAMPLE_CPU"] = "1"
        os.execv(sys.executable, [sys.executable] + sys.argv)

import numpy as np

from sparse_coding__tpu import build_ensemble
from sparse_coding__tpu.data import RandomDatasetGenerator
from sparse_coding__tpu.ensemble import Ensemble
from sparse_coding__tpu.parallel import make_mesh
from sparse_coding__tpu.train import checkpoint as ckpt


def main():
    gen = RandomDatasetGenerator(
        activation_dim=32, n_ground_truth_components=64, batch_size=256,
        feature_num_nonzero=5, feature_prob_decay=0.995, correlated=False,
        key=jax.random.PRNGKey(0),
    )
    from sparse_coding__tpu.models import FunctionalTiedSAE

    ens = build_ensemble(
        FunctionalTiedSAE, jax.random.PRNGKey(1),
        [{"l1_alpha": a} for a in (1e-4, 3e-4, 1e-3, 3e-3)],
        optimizer_kwargs={"learning_rate": 1e-3},
        activation_size=32, n_dict_components=64,
    ).shard(make_mesh(2, 2, 2, devices=jax.devices()[:8]))  # model x data x dict
    print("training on mesh (model=2, data=2, dict=2)...")
    for _ in range(20):
        loss_dict, _ = ens.step_batch(next(gen))

    with tempfile.TemporaryDirectory() as tmp:
        ckpt.save_ensemble_checkpoint(
            Path(tmp) / "ckpt_19", [(ens, {}, "sweep")], chunk_cursor=19
        )
        print("checkpoint saved; simulating a preemption...")

        # the job comes back with a different topology: 4 devices
        tree = ckpt.restore_ensemble_checkpoint(
            Path(tmp) / "ckpt_19",
            template={"cursor": {"chunk": 0},
                      "ensembles": {"sweep": ens.state_dict()},
                      "args": {"sweep": {}}},
        )
        resumed = Ensemble.from_state(tree["ensembles"]["sweep"]).shard(
            make_mesh(1, 2, 2, devices=jax.devices()[:4])
        )
        print(f"resumed at chunk {int(tree['cursor']['chunk'])} on mesh "
              "(model=1, data=2, dict=2) — half the devices")
        batch = next(gen)
        l_resumed, _ = resumed.step_batch(batch)
        l_control, _ = ens.step_batch(batch)
        a = np.asarray(jax.device_get(l_resumed["loss"]))
        b = np.asarray(jax.device_get(l_control["loss"]))
        np.testing.assert_allclose(a, b, rtol=1e-5)
        print(f"continued losses match the original mesh: {a}")


if __name__ == "__main__":
    main()
