"""Autointerp artifact on a PRETRAINED subject (round-3 follow-through of
VERDICT r2 missing #1: every prior interp exercise ran on random-init
subjects whose activations have near-toy statistics).

Pipeline, all in-image (zero egress):
  1. pretrain the pythia-70m-geometry subject on the trigram language
     (`lm.pretrain`, ~90 s on-chip to ~0.3 nats);
  2. harvest mid-layer residual activations from held-out corpus rows and
     train a small tied-SAE l1 grid on them;
  3. run the full autointerp protocol (df → explain → simulate → score,
     `interp.pipeline.run`) with the deterministic offline client on the
     best SAE member AND on sparsity-matched baselines (random dict,
     identity-relu) — the reference's score-vs-baseline comparison
     (`interpret.py:388-399` + plot_autointerp_vs_baselines);
  4. write INTERP_<round>.json: per-transform top-and-random scores. The
     SAE must beat the random-dict floor for the artifact to be healthy.

Run: `python scripts/interp_subject_run.py` (chip, ~10-15 min). `--quick` is the
CPU-sized smoke mode used by the test suite.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time
from pathlib import Path

import numpy as np

REPO = Path(__file__).resolve().parent.parent
ROUND_TAG = os.environ.get("PARITY_ROUND", "r04")

if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--out", default=None)
    ap.add_argument("--pretrain", type=int, default=None)
    args = ap.parse_args(argv)

    from sparse_coding__tpu.utils.compile_cache import enable_persistent_compile_cache

    enable_persistent_compile_cache()

    import jax
    import jax.numpy as jnp

    from parity_run import build_subject_model, harvest_rows, maybe_pretrain
    from sparse_coding__tpu import build_ensemble
    from sparse_coding__tpu.data.activations import harvest_to_device
    from sparse_coding__tpu.interp import pipeline
    from sparse_coding__tpu.interp.clients import TokenLexiconClient
    from sparse_coding__tpu.models import FunctionalTiedSAE
    from sparse_coding__tpu.models.learned_dict import IdentityReLU, RandomDict
    from sparse_coding__tpu.train.loop import ensemble_train_loop
    from sparse_coding__tpu.utils.config import InterpArgs

    t_start = time.time()
    quick = args.quick
    seq_len = 32 if quick else 256
    frag_len = 16 if quick else 64
    batch_rows = 16 if quick else 64
    # r4: convergence-scale SAE training (the r3 artifact's 0.19-vs-0.10
    # SAE-vs-random gap was measured on a 2-chunk smoke-trained SAE)
    chunk_gb = 0.002 if quick else 0.25
    n_chunks = 2 if quick else 6
    n_epochs = 1 if quick else 5
    layer, layer_loc = (1, "residual") if quick else (2, "residual")
    ratio = 2 if quick else 4
    sae_batch = 256 if quick else 2048
    n_feats_explain = 6 if quick else 80
    # the df lives in a tempdir and dies with the run: sizing it beyond the
    # explained set is pure dead work here
    df_n_feats = 12 if quick else 80
    n_fragments = 256 if quick else 4000
    pretrain_steps = args.pretrain if args.pretrain is not None else (
        40 if quick else 2000
    )

    print("Building + pretraining subject...")
    lm_cfg, params = build_subject_model(quick, "neox")
    d_act = lm_cfg.d_model
    n_dict = ratio * d_act
    params, lang, pretrain_stats = maybe_pretrain(params, lm_cfg, quick, pretrain_steps)
    assert lang is not None, "this artifact requires a pretrained subject"

    report: dict = {
        "config": {
            "subject": f"{lm_cfg.arch} d={d_act} L={lm_cfg.n_layers} "
            "(pythia-70m geometry, trigram-pretrained)",
            "layer": layer, "layer_loc": layer_loc, "n_dict": n_dict,
            "n_feats_explain": n_feats_explain, "df_n_feats": df_n_feats,
            "client": "TokenLexiconClient (deterministic offline)",
            "device": jax.devices()[0].device_kind,
        },
        # VERDICT r4 next #8: the offline-proxy caveat at the artifact level
        "subject_caveat": (
            "Subject is a trigram-pretrained synthetic-language LM (zero-"
            "egress image) and the scorer is the offline TokenLexiconClient "
            "proxy — these scores are NOT comparable to the reference's "
            "GPT-4-explain/davinci-simulate numbers (interpret.py:334-358); "
            "they demonstrate the pipeline and the SAE-vs-baseline ordering "
            "only. Run interp with OpenAIClient on a networked machine for "
            "comparable scores."
        ),
        "pretrain": pretrain_stats,
    }

    with tempfile.TemporaryDirectory(prefix="interp_subject_") as tmp:
        n_rows = harvest_rows(d_act, chunk_gb, batch_rows, seq_len, n_chunks)
        tokens = lang.sample(n_rows, seq_len, seed=21)
        print(f"Harvesting {n_chunks} chunks ({n_rows * seq_len:,} tokens, fused)...")
        train_dtype = jnp.float32 if quick else jnp.bfloat16
        train_chunks = [
            chunk[(layer, layer_loc)].astype(train_dtype)
            for chunk in harvest_to_device(
                params, lm_cfg, tokens, [layer], [layer_loc],
                batch_size=batch_rows, chunk_size_gb=chunk_gb, n_chunks=n_chunks,
            )
        ]

        print("Training the SAE grid...")
        grid = [3e-4, 1e-3] if quick else [3e-4, 1e-3, 3e-3]
        ens = build_ensemble(
            FunctionalTiedSAE, jax.random.PRNGKey(0),
            [{"l1_alpha": a} for a in grid],
            optimizer_kwargs={"learning_rate": 1e-3},
            activation_size=d_act, n_dict_components=n_dict,
            compute_dtype=None if quick else jnp.bfloat16,
        )
        key = jax.random.PRNGKey(1)
        for _epoch in range(n_epochs):
            for chunk in train_chunks:
                key, k = jax.random.split(key)
                ensemble_train_loop(ens, chunk, batch_size=sae_batch, key=k)
        del train_chunks
        dicts = ens.to_learned_dicts()
        # middle-of-grid member: the reference's sweet spot for interp
        sae = dicts[len(dicts) // 2]

        subjects = {
            f"tied_sae_l1={grid[len(dicts) // 2]:g}": sae,
            "random_dict": RandomDict(
                d_act, n_feats=n_dict, key=jax.random.PRNGKey(9)
            ),
            "identity_relu": IdentityReLU(d_act),
        }

        fragments = lang.sample(n_fragments, frag_len, seed=31)
        decode = lambda row: [f"t{int(t)}" for t in row]
        client = TokenLexiconClient()
        report["scores"] = {}
        for name, ld in subjects.items():
            print(f"Autointerp: {name}...")
            icfg = InterpArgs(
                layer=layer, layer_loc=layer_loc,
                n_feats_explain=n_feats_explain, df_n_feats=df_n_feats,
                save_loc=f"{tmp}/interp_{name}",
            )
            t0 = time.time()
            results = pipeline.run(
                ld, icfg, params, lm_cfg, fragments, decode, client=client
            )
            scores = results["score"].astype(float)
            report["scores"][name] = {
                "mean": round(float(scores.mean()), 4),
                "std": round(float(scores.std()), 4),
                "n": int(len(scores)),
                "seconds": round(time.time() - t0, 1),
            }
            print(f"  mean {report['scores'][name]['mean']} "
                  f"({report['scores'][name]['seconds']}s)")

    sae_name = next(iter(report["scores"]))
    report["healthy"] = bool(
        report["scores"][sae_name]["mean"] > report["scores"]["random_dict"]["mean"]
    )
    report["total_seconds"] = round(time.time() - t_start, 1)

    out = Path(args.out) if args.out else REPO
    out.mkdir(parents=True, exist_ok=True)
    path = out / f"INTERP_{ROUND_TAG}{'_quick' if quick else ''}.json"
    with open(path, "w") as f:
        json.dump(report, f, indent=1)
    print(f"Wrote {path} (healthy={report['healthy']})")


if __name__ == "__main__":
    main()
