"""True multi-process distributed training test (SURVEY.md §2.4 P6).

N OS processes (2 and 4 tested) — each a simulated pod 'host' owning
8//N virtual CPU devices — are wired into one 8-device global mesh by `parallel.distributed.
initialize_distributed` (gloo transport standing in for ICI/DCN; the jax
program is identical to a real pod's). Each runs the framework's sharded
ensemble step over the (model=2, data=2, dict=2) mesh with globally-sharded
batches, and the all-gathered losses must (a) agree across processes and
(b) match a single-process run of the same mesh bit-for-bit-close.

The reference had NO distributed tests at all (SURVEY.md §4 "Distributed
testing: none"); its nearest analogue is the untested gloo DDP experiment
(`experiments/huge_batch_size.py:337-345`).
"""

import os
import socket
import subprocess
import sys
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

REPO = Path(__file__).resolve().parent.parent


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.slow
@pytest.mark.parametrize(
    "n_proc,mode",
    [
        (2, "default"),
        (4, "default"),
        # 2 hosts x 4 devices at the 32x-overcomplete dictpar shape: the
        # dict axis stays within each host (ICI), the data axis crosses the
        # host (DCN) boundary — the real pod layout for BASELINE config 5
        # (VERDICT r4 next #6)
        (2, "dictpar"),
    ],
)
def test_n_process_sharded_step_matches_single_process(devices, n_proc, mode):
    port = _free_port()
    procs = [
        subprocess.Popen(
            [
                sys.executable,
                str(REPO / "tests" / "_multiprocess_worker.py"),
                str(pid), str(n_proc), f"127.0.0.1:{port}", mode,
            ],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        )
        for pid in range(n_proc)
    ]
    outs = []
    for p in procs:
        out, err = p.communicate(timeout=300)
        assert p.returncode == 0, err[-2000:]
        outs.append(out)
    losses = []
    for out in outs:
        line = next(l for l in out.splitlines() if l.startswith("LOSSES="))
        losses.append(np.array([float(v) for v in line[7:].split(",")]))
    # every process observes the same global losses
    for other in losses[1:]:
        np.testing.assert_array_equal(losses[0], other)

    # single-process reference on the same 8-device mesh, same seeds/batches
    from sparse_coding__tpu import build_ensemble
    from sparse_coding__tpu.models import FunctionalTiedSAE
    from sparse_coding__tpu.parallel import make_mesh

    sys.path.insert(0, str(REPO / "tests"))
    from _multiprocess_worker import worker_config

    d_act, n_dict, batch, mesh_shape = worker_config(mode)
    ens = build_ensemble(
        FunctionalTiedSAE,
        jax.random.PRNGKey(0),
        [{"l1_alpha": a} for a in (1e-4, 3e-4, 1e-3, 3e-3)],
        optimizer_kwargs={"learning_rate": 1e-3},
        activation_size=d_act,
        n_dict_components=n_dict,
    ).shard(make_mesh(*mesh_shape))
    for step in range(3):
        full = jax.random.normal(jax.random.PRNGKey(100 + step), (batch, d_act))
        loss_dict, _ = ens.step_batch(full)
    ref = np.asarray(jax.device_get(loss_dict["loss"]))
    np.testing.assert_allclose(losses[0], ref, rtol=1e-5)


@pytest.mark.slow
def test_two_process_telemetry_merges_and_detects_straggler(tmp_path, devices):
    """ISSUE 4 acceptance: a real two-process gloo run writes per-process
    event logs; the merged report carries one row per host and a straggler
    section; an injected slow host (p1 sleeps 0.25 s per chunk) trips the
    `skew.flush.*` gauges; a deliberately disagreeing config surfaces as a
    hard `desync` anomaly; the monitor renders the run dir."""
    port = _free_port()
    run_dir = tmp_path / "pod_run"
    sleep_s = 0.25
    procs = []
    for pid in range(2):
        env = dict(os.environ)
        env["SC_TEST_DESYNC"] = "1"  # config poisoned with the process id
        if pid == 1:
            env["SC_TEST_CHUNK_SLEEP"] = str(sleep_s)
        procs.append(
            subprocess.Popen(
                [
                    sys.executable,
                    str(REPO / "tests" / "_multiprocess_worker.py"),
                    str(pid), "2", f"127.0.0.1:{port}", "telemetry",
                    str(run_dir),
                ],
                stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
                env=env,
            )
        )
    for p in procs:
        out, err = p.communicate(timeout=300)
        assert p.returncode == 0, err[-2000:]

    from sparse_coding__tpu.telemetry import read_events
    from sparse_coding__tpu.telemetry.report import load_run, render_markdown

    # per-process logs, every record tagged with its originating host
    events = {}
    for pid in range(2):
        path = run_dir / f"events.p{pid}.jsonl"
        assert path.exists(), f"missing per-process log {path}"
        events[pid] = read_events(path)
        assert all(e["process_index"] == pid for e in events[pid])
        kinds = [e["event"] for e in events[pid]]
        assert kinds.count("heartbeat") == 3
        assert kinds[0] == "run_start" and kinds[-1] == "run_end"

    # clock offset measured at initialize_distributed rides the fingerprint
    fp = events[1][0]["fingerprint"]
    assert "clock_offset_seconds" in fp

    # the injected straggler trips the skew gauges (last snapshot)
    snaps = [e for e in events[0] if e["event"] == "snapshot"]
    gauges = snaps[-1]["gauges"]
    assert gauges["skew.flush.spread_seconds"] >= 0.6 * sleep_s, gauges
    # and both hosts agree on the allgathered skew
    snaps1 = [e for e in events[1] if e["event"] == "snapshot"]
    assert (
        snaps1[-1]["gauges"]["skew.flush.spread_seconds"]
        == gauges["skew.flush.spread_seconds"]
    )

    # the poisoned config is a hard desync anomaly on both hosts
    for pid in range(2):
        desync = [
            e for e in events[pid]
            if e["event"] == "anomaly" and e["kind"] == "desync"
        ]
        assert desync and desync[0]["processes"] == [1]

    # merged report: one row per host + straggler section + desync diff
    md = render_markdown(load_run(run_dir))
    assert "Pod / multi-host" in md
    assert "| p0 |" in md and "| p1 |" in md
    assert "Straggler skew" in md
    assert "desync" in md.lower()
    assert "config" in md  # the disagreeing field is named

    # the monitor renders the same dir (exit 0 = no malformed lines)
    from sparse_coding__tpu.monitor import main as monitor_main

    assert monitor_main([str(run_dir), "--once"]) == 0
