"""Prometheus text-exposition export for the live telemetry bus (ISSUE 14).

The `RunTelemetry` counters/gauges/histograms were write-only JSONL until
now; this module renders them in the Prometheus text exposition format
(version 0.0.4) so any scraper — or `monitor --scrape`, or the SLO
engine's live mode — can pull them:

  - counters  → ``sc_<name>_total`` (``# TYPE ... counter``)
  - gauges    → ``sc_<name>``       (``# TYPE ... gauge``)
  - histograms→ ``sc_<name>_bucket{le="..."}`` cumulative series plus
    ``_sum``/``_count`` (`RunTelemetry.hist_observe`'s fixed log-spaced
    buckets)

Metric names are sanitized (``serve.latency_p50_ms`` →
``sc_serve_latency_p50_ms``); label values are escaped per the spec
(backslash, double-quote, newline). Output ordering is sorted and stable —
a golden-file contract (tests/golden/metrics_exposition.txt).

Mounted as ``GET /metrics`` on the serve server, the router, and the
replicaset CLI (`serve_metrics_server`); fleet workers, which own no HTTP
listener, write the same text to a per-worker ``metrics/<worker>.prom``
file (`write_metrics_file`) that the fleet report aggregates.

`parse_prometheus` / `scrape` are the read side: they turn exposition text
back into ``{name: [(labels, value), ...]}`` families, with
`histogram_from_families` + `histogram_quantile` recovering latency
quantiles from the bucket series (docs/observability.md §8).
"""

from __future__ import annotations

import json
import re
import threading
import time
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from pathlib import Path
from typing import Any, Callable, Dict, List, Optional, Tuple

__all__ = [
    "PREFIX",
    "CONTENT_TYPE",
    "metric_name",
    "render_prometheus",
    "telemetry_metrics_text",
    "write_metrics_file",
    "parse_prometheus",
    "scrape",
    "histogram_from_families",
    "histogram_quantile",
    "MetricsServer",
    "serve_metrics_server",
]

PREFIX = "sc_"
CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")


def sanitize_key(key: str) -> str:
    """Telemetry key → exposition-safe name fragment (dots and other
    illegal characters become underscores). THE one sanitizer — the SLO
    engine's scrape mode maps objective keys through it so its lookups
    can never diverge from what `metric_name` emitted."""
    return _NAME_RE.sub("_", str(key))


def metric_name(key: str, suffix: str = "") -> str:
    """Telemetry key → Prometheus metric name: prefix, sanitize, suffix
    (``serve.requests`` → ``sc_serve_requests_total``)."""
    return PREFIX + sanitize_key(key) + suffix


def _escape_label(v: str) -> str:
    return (
        str(v).replace("\\", r"\\").replace('"', r"\"").replace("\n", r"\n")
    )


def _labels_str(labels: Optional[Dict[str, Any]],
                extra: Optional[Dict[str, Any]] = None) -> str:
    merged: Dict[str, Any] = {}
    merged.update(labels or {})
    merged.update(extra or {})
    if not merged:
        return ""
    inner = ",".join(
        f'{_NAME_RE.sub("_", str(k))}="{_escape_label(v)}"'
        for k, v in sorted(merged.items())
    )
    return "{" + inner + "}"


def _fmt_value(v: float) -> str:
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def render_prometheus(
    counters: Optional[Dict[str, float]] = None,
    gauges: Optional[Dict[str, float]] = None,
    hists: Optional[Dict[str, Dict[str, Any]]] = None,
    labels: Optional[Dict[str, Any]] = None,
) -> str:
    """The exposition text for one writer's counters/gauges/histograms.

    ``hists`` entries are `RunTelemetry.hists` dicts: ``{"bounds": [...],
    "counts": [per-bucket..., overflow], "sum": float, "count": int}`` —
    rendered as the cumulative ``_bucket`` series the quantile math wants.
    Ordering is sorted by metric name: byte-stable for fixed inputs.
    """
    lines: List[str] = []
    for key, v in sorted((counters or {}).items()):
        name = metric_name(key, "_total")
        lines.append(f"# TYPE {name} counter")
        lines.append(f"{name}{_labels_str(labels)} {_fmt_value(v)}")
    for key, v in sorted((gauges or {}).items()):
        name = metric_name(key)
        lines.append(f"# TYPE {name} gauge")
        lines.append(f"{name}{_labels_str(labels)} {_fmt_value(v)}")
    for key, h in sorted((hists or {}).items()):
        name = metric_name(key)
        lines.append(f"# TYPE {name} histogram")
        cum = 0
        for bound, n in zip(h["bounds"], h["counts"]):
            cum += int(n)
            lines.append(
                f"{name}_bucket"
                f"{_labels_str(labels, {'le': _fmt_value(bound)})} {cum}"
            )
        cum += int(h["counts"][len(h["bounds"])])
        lines.append(f"{name}_bucket{_labels_str(labels, {'le': '+Inf'})} {cum}")
        lines.append(f"{name}_sum{_labels_str(labels)} {_fmt_value(h['sum'])}")
        lines.append(f"{name}_count{_labels_str(labels)} {cum}")
    return "\n".join(lines) + ("\n" if lines else "")


def telemetry_metrics_text(telemetry, uptime: bool = True) -> str:
    """One live `RunTelemetry`'s full exposition (its constant ``tags``
    become labels on every series; ``sc_uptime_seconds`` rides along)."""
    gauges = dict(telemetry.gauges)
    if uptime:
        gauges["uptime_seconds"] = round(time.time() - telemetry._t0, 3)
    return render_prometheus(
        counters=telemetry.counters,
        gauges=gauges,
        hists=telemetry.hists,
        labels=telemetry.tags or None,
    )


def write_metrics_file(telemetry, path) -> Path:
    """Atomically publish a telemetry handle's exposition text to ``path``
    (the fleet worker's HTTP-less export — the fleet report aggregates
    ``metrics/*.prom``). Same-dir temp + ``os.replace``: a reader never
    sees a torn file."""
    import os

    p = Path(path)
    p.parent.mkdir(parents=True, exist_ok=True)
    tmp = p.parent / f".{p.name}.tmp"
    tmp.write_text(telemetry_metrics_text(telemetry))
    os.replace(tmp, p)
    return p


# -- the read side ------------------------------------------------------------

_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>.*)\})?\s+(?P<value>\S+)\s*$"
)
_LABEL_RE = re.compile(r'(\w+)="((?:[^"\\]|\\.)*)"')
_UNESCAPE_RE = re.compile(r"\\(.)")
_UNESCAPES = {"n": "\n", "\\": "\\", '"': '"'}


def _unescape_label(v: str) -> str:
    # one left-to-right scan: chained str.replace would corrupt a literal
    # backslash followed by 'n' (r'C:\new' round-trips wrong otherwise)
    return _UNESCAPE_RE.sub(
        lambda m: _UNESCAPES.get(m.group(1), m.group(1)), v
    )


def parse_prometheus(text: str) -> Dict[str, List[Tuple[Dict[str, str], float]]]:
    """Exposition text → ``{metric_name: [(labels, value), ...]}``. Unknown
    lines and comments are skipped (a scraper must tolerate foreign
    families)."""
    out: Dict[str, List[Tuple[Dict[str, str], float]]] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            continue
        labels = {
            k: _unescape_label(v)
            for k, v in _LABEL_RE.findall(m.group("labels") or "")
        }
        try:
            value = float(m.group("value"))
        except ValueError:
            continue
        out.setdefault(m.group("name"), []).append((labels, value))
    return out


def scrape(url: str, timeout: float = 3.0) -> Dict[str, List[Tuple[Dict[str, str], float]]]:
    """GET a ``/metrics`` endpoint and parse it. ``url`` may be the bare
    server base (``http://host:port``) — ``/metrics`` is appended when
    missing."""
    u = url.rstrip("/")
    if not u.endswith("/metrics"):
        u += "/metrics"
    with urllib.request.urlopen(u, timeout=timeout) as resp:
        return parse_prometheus(resp.read().decode("utf-8", errors="replace"))


def family_value(
    families: Dict[str, List[Tuple[Dict[str, str], float]]],
    key: str, suffix: str = "", default: Optional[float] = None,
) -> Optional[float]:
    """Sum of a family's samples across label sets (the common merge for a
    counter scraped from several writers)."""
    samples = families.get(metric_name(key, suffix))
    if not samples:
        return default
    return sum(v for _, v in samples)


def histogram_from_families(
    families: Dict[str, List[Tuple[Dict[str, str], float]]], key: str
) -> Optional[Dict[str, Any]]:
    """Recover one histogram from its ``_bucket``/``_sum``/``_count``
    series (bucket counts summed across label sets — scraping N replicas
    merges into one tier-wide histogram). None when absent."""
    name = metric_name(key)
    buckets = families.get(name + "_bucket")
    if not buckets:
        return None
    by_le: Dict[float, float] = {}
    for labels, v in buckets:
        le = labels.get("le", "+Inf")
        bound = float("inf") if le == "+Inf" else float(le)
        by_le[bound] = by_le.get(bound, 0.0) + v
    bounds = sorted(b for b in by_le if b != float("inf"))
    return {
        "bounds": bounds,
        "cumulative": [by_le[b] for b in bounds],
        "count": by_le.get(float("inf"), max(by_le.values()) if by_le else 0.0),
        "sum": family_value(families, key, "_sum", 0.0),
    }


def histogram_quantile(hist: Dict[str, Any], q: float) -> Optional[float]:
    """The standard conservative bucket quantile: the upper bound of the
    first bucket whose cumulative count reaches ``q * count``. The true
    quantile lies within one bucket width below the returned bound —
    exactly the tolerance the /metrics-vs-gauges acceptance pins."""
    count = hist.get("count") or 0
    if count <= 0:
        return None
    rank = q * count
    for bound, cum in zip(hist["bounds"], hist["cumulative"]):
        if cum >= rank:
            return float(bound)
    return float("inf")


# -- the standalone metrics listener ------------------------------------------


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):  # pragma: no cover - quiet by design
        pass

    def do_GET(self):
        if self.path != "/metrics":
            body = json.dumps({"error": f"no route {self.path}"}).encode()
            self.send_response(404)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        try:
            body = self.server.render().encode()
        except Exception as e:  # the exporter must never take a process down
            body = f"# render failed: {e!r}\n".encode()
        self.send_response(200)
        self.send_header("Content-Type", CONTENT_TYPE)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)


class MetricsServer:
    """A tiny standalone ``GET /metrics`` listener for processes whose main
    API has no HTTP surface of its own (the replicaset CLI) or for tests
    that need fake scrape endpoints. ``render`` is any () → str callable."""

    def __init__(self, render: Callable[[], str], host: str = "127.0.0.1",
                 port: int = 0):
        self.httpd = ThreadingHTTPServer((host, port), _Handler)
        self.httpd.daemon_threads = True
        self.httpd.render = render
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self.httpd.server_address[1]

    @property
    def address(self) -> str:
        host, port = self.httpd.server_address[:2]
        return f"http://{host}:{port}"

    def start(self) -> "MetricsServer":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self.httpd.serve_forever, daemon=True,
                name="metrics-http",
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        if self._thread is not None:
            self.httpd.shutdown()
            self.httpd.server_close()
            self._thread = None

    def __enter__(self) -> "MetricsServer":
        return self.start()

    def __exit__(self, exc_type, exc, tb):
        self.stop()
        return False


def serve_metrics_server(telemetry, host: str = "127.0.0.1",
                         port: int = 0) -> MetricsServer:
    """A started `MetricsServer` exporting one telemetry handle."""
    return MetricsServer(
        lambda: telemetry_metrics_text(telemetry), host=host, port=port
    ).start()
