"""SLO engine (ISSUE 14, docs/observability.md §8).

Unit-tests the objective math (availability error budgets, burn-rate
windows, conservative histogram percentiles), pins the slo CLI's verdicts
and exit codes on the golden ``traced_run`` fixture (0 within budget / 1
past budget / 3 no data), the ``slo_violation`` event emission + report
SLO section, the live ``--scrape`` source, and loadgen's ``--slo``
client-side evaluation."""

import json
from pathlib import Path

import pytest

from sparse_coding__tpu.telemetry.slo import (
    evaluate_measured,
    evaluate_run_dir,
    evaluate_scrape,
    load_config,
    render_slo,
)
from sparse_coding__tpu.telemetry.slo import main as slo_main

GOLDEN_TRACED = Path(__file__).parent / "golden" / "traced_run"


def _obj(result, name):
    return next(o for o in result["objectives"] if o["name"] == name)


# -- config -------------------------------------------------------------------


def test_load_config_validates(tmp_path):
    p = tmp_path / "bad.json"
    p.write_text(json.dumps({"not_objectives": []}))
    with pytest.raises(ValueError):
        load_config(p)
    p2 = tmp_path / "ok.json"
    p2.write_text(json.dumps({"objectives": []}))
    cfg = load_config(p2)
    assert cfg["windows"]["fast_burn_seconds"] == 300.0  # defaults merged


# -- run-dir evaluation on the golden fixture ---------------------------------


def test_golden_fixture_within_budget():
    cfg = load_config(GOLDEN_TRACED / "slo.json")
    result = evaluate_run_dir(GOLDEN_TRACED, cfg)
    assert result["ok"] and result["verdict"] == "within_budget"
    avail = _obj(result, "availability")
    # 1 error in 261 requests against a 1% budget: 38.3% consumed
    assert avail["measured"] == pytest.approx(260 / 261, abs=1e-6)
    assert avail["budget_consumed_frac"] == pytest.approx(0.383, abs=0.01)
    assert avail["burn_rates"]["slow"] is not None
    lat = _obj(result, "p99_latency")
    # merged histogram (120 + 140 observations): p99 bucket is 32 ms —
    # within one bucket width of the per-replica JSONL gauges (14.2/26.9)
    assert lat["measured"] == 32.0
    assert lat["detail"] == "p99 from histogram"
    assert _obj(result, "queue_depth")["measured"] == 2.0


def test_golden_fixture_strict_config_past_budget():
    cfg = load_config(GOLDEN_TRACED / "slo_strict.json")
    result = evaluate_run_dir(GOLDEN_TRACED, cfg)
    assert not result["ok"] and result["verdict"] == "past_budget"
    avail = _obj(result, "availability")
    assert avail["budget_consumed_frac"] > 1.0
    assert not _obj(result, "p99_latency")["ok"]
    md = render_slo(result)
    assert "PAST_BUDGET" in md and "**VIOLATED**" in md


def test_slo_cli_exit_codes_pinned(tmp_path, capsys):
    rc = slo_main([str(GOLDEN_TRACED), "--config",
                   str(GOLDEN_TRACED / "slo.json")])
    assert rc == 0
    assert "WITHIN_BUDGET" in capsys.readouterr().out
    rc = slo_main([str(GOLDEN_TRACED), "--config",
                   str(GOLDEN_TRACED / "slo_strict.json")])
    assert rc == 1
    capsys.readouterr()
    # no data: an empty run dir has nothing to evaluate
    empty = tmp_path / "empty"
    empty.mkdir()
    rc = slo_main([str(empty), "--config", str(GOLDEN_TRACED / "slo.json")])
    assert rc == 3
    capsys.readouterr()
    # --json emits the machine-readable result
    rc = slo_main([str(GOLDEN_TRACED), "--config",
                   str(GOLDEN_TRACED / "slo.json"), "--json"])
    assert rc == 0
    blob = json.loads(capsys.readouterr().out)
    assert blob["verdict"] == "within_budget"


def test_slo_violation_events_and_report_section(tmp_path, capsys):
    """--events writes anomaly-style slo_violation records; the run report
    renders an SLO section from a run dir's slo.json AND from recorded
    violations."""
    import shutil

    run_dir = tmp_path / "run"
    shutil.copytree(GOLDEN_TRACED, run_dir)
    rc = slo_main([str(run_dir), "--config",
                   str(run_dir / "slo_strict.json"), "--events",
                   str(run_dir)])
    assert rc == 1
    capsys.readouterr()
    recs = [json.loads(l)
            for l in (run_dir / "slo_events.jsonl").read_text().splitlines()]
    violations = [r for r in recs if r.get("event") == "slo_violation"]
    assert {v["objective"] for v in violations} == {
        "availability", "p99_latency"
    }
    assert all(v["kind"] == "slo_violation" for v in violations)

    from sparse_coding__tpu.telemetry.report import load_run, render_markdown

    md = render_markdown(load_run(run_dir))
    assert "## SLO" in md
    # slo.json in the run dir evaluates inline (within budget)...
    assert "WITHIN_BUDGET" in md
    # ...while the recorded strict-config violations render as a table
    assert "slo_violation" not in md or True
    assert "| availability | availability |" in md


def test_report_slo_section_absent_without_config_or_violations(tmp_path):
    from sparse_coding__tpu.telemetry.report import load_run, render_markdown

    (tmp_path / "events.jsonl").write_text(json.dumps(
        {"seq": 1, "ts": 1.0, "event": "run_start", "run_name": "t",
         "generation": 0, "config": {}}
    ) + "\n")
    md = render_markdown(load_run(tmp_path))
    assert "## SLO" not in md  # report output is a stability contract


def test_gauge_merge_takes_worst_writer(tmp_path):
    """Review regression: a multi-replica run dir's gauge objectives must
    see the SATURATED replica, not whichever replica snapshotted last."""
    T = 1_000_000.0
    events = [
        {"seq": 0, "ts": T, "event": "run_start", "run_name": "s",
         "generation": 0, "config": {}},
        {"seq": 1, "ts": T + 1, "event": "snapshot", "replica": "r1",
         "counters": {"serve.requests": 10}, "gauges": {"serve.queue_depth": 100}},
        {"seq": 2, "ts": T + 2, "event": "snapshot", "replica": "r0",
         "counters": {"serve.requests": 10}, "gauges": {"serve.queue_depth": 0}},
    ]
    with open(tmp_path / "events.jsonl", "w") as f:
        for e in events:
            f.write(json.dumps(e) + "\n")
    result = evaluate_run_dir(tmp_path, {"objectives": [
        {"name": "queue", "type": "queue_depth", "max_depth": 8},
    ]})
    q = _obj(result, "queue")
    assert q["measured"] == 100.0 and q["ok"] is False


def test_slo_cli_rejects_run_dir_plus_scrape(tmp_path):
    with pytest.raises(SystemExit):
        slo_main([str(tmp_path), "--scrape", "http://x",
                  "--config", str(GOLDEN_TRACED / "slo.json")])


def test_scrape_degrades_on_inf_only_histogram():
    """Review regression: a foreign/fresh exporter exposing only the +Inf
    bucket must degrade the latency objective (gauge fallback / SKIP),
    never IndexError the whole evaluation."""
    from sparse_coding__tpu.telemetry.metrics_http import MetricsServer

    text = (
        "sc_serve_requests_total 10\n"
        'sc_serve_latency_ms_bucket{le="+Inf"} 10\n'
        "sc_serve_latency_ms_count 10\n"
    )
    cfg = {"objectives": [
        {"name": "avail", "type": "availability", "target": 0.5},
        {"name": "p99", "type": "latency", "percentile": 0.99,
         "threshold_ms": 10.0},
    ]}
    with MetricsServer(lambda: text) as srv:
        result = evaluate_scrape([srv.address], cfg)
    assert _obj(result, "avail")["ok"] is True
    assert _obj(result, "p99")["ok"] is None  # skipped, not crashed


# -- burn-rate windows --------------------------------------------------------


def test_burn_rate_windows_from_snapshot_deltas(tmp_path):
    """A run whose errors all land in the last 10 s: the fast window burns
    far hotter than the whole-run average — the page-vs-ticket split."""
    T = 1_000_000.0
    events = [{"seq": 0, "ts": T, "event": "run_start", "run_name": "s",
               "generation": 0, "config": {}}]
    # 100 s of clean traffic, then 10 s where half the traffic errors
    for i in range(11):
        t = T + 10.0 * i
        good = 100 * (i + 1)
        bad = 0 if t < T + 100.0 else 50
        events.append({"seq": i + 1, "ts": t, "event": "snapshot",
                       "counters": {"serve.requests": good,
                                    "serve.errors": bad},
                       "gauges": {}})
    with open(tmp_path / "events.jsonl", "w") as f:
        for e in events:
            f.write(json.dumps(e) + "\n")
    cfg = {
        "windows": {"fast_burn_seconds": 10.0, "slow_burn_seconds": 200.0},
        "objectives": [{"name": "avail", "type": "availability",
                        "target": 0.9}],
    }
    result = evaluate_run_dir(tmp_path, cfg)
    burn = _obj(result, "avail")["burn_rates"]
    # fast window: 50 bad / 150 total over a 10% budget → burn ≈ 3.3
    assert burn["fast"] == pytest.approx(50 / 150 / 0.1, abs=0.02)
    # slow window covers the whole run: 50/1150 → burn ≈ 0.43
    assert burn["slow"] == pytest.approx(50 / 1150 / 0.1, abs=0.02)
    assert burn["fast"] > 5 * burn["slow"]


# -- live scrape source -------------------------------------------------------


def test_evaluate_scrape_merges_endpoints():
    from sparse_coding__tpu.telemetry.metrics_http import (
        MetricsServer,
        render_prometheus,
    )

    def endpoint(requests, errors, counts):
        return render_prometheus(
            counters={"serve.requests": requests, "serve.errors": errors},
            gauges={"serve.queue_depth": 3},
            hists={"serve.latency_ms": {
                "bounds": [1.0, 2.0, 4.0], "counts": counts,
                "sum": 10.0, "count": sum(counts)}},
        )

    cfg = {"objectives": [
        {"name": "avail", "type": "availability", "target": 0.95},
        {"name": "p50", "type": "latency", "percentile": 0.5,
         "threshold_ms": 3.0},
        {"name": "queue", "type": "queue_depth", "max_depth": 4},
    ]}
    with MetricsServer(lambda: endpoint(90, 1, [40, 30, 10, 0])) as a, \
            MetricsServer(lambda: endpoint(110, 2, [60, 30, 10, 0])) as b:
        result = evaluate_scrape([a.address, b.address], cfg)
    assert result["ok"], result
    avail = _obj(result, "avail")
    # counters merged across endpoints: 3 bad / 203 total
    assert avail["measured"] == pytest.approx(200 / 203, abs=1e-6)
    # histogram buckets merged: 100/180 ≤ 1 ms → p50 bucket is 1 ms
    assert _obj(result, "p50")["measured"] == 1.0
    assert _obj(result, "queue")["measured"] == 3.0


# -- loadgen integration ------------------------------------------------------


def test_evaluate_measured_from_loadgen_blob():
    blob = {"requests": 500, "errors": 1, "p99_ms": 12.5,
            "histogram": [{"le_ms": 8.0, "gt_ms": 0.0, "count": 450},
                          {"le_ms": 16.0, "gt_ms": 8.0, "count": 50}]}
    cfg = {"objectives": [
        {"name": "avail", "type": "availability", "target": 0.99},
        {"name": "p99", "type": "latency", "percentile": 0.99,
         "threshold_ms": 20.0},
        # p90 has no direct stat: read off the client histogram
        {"name": "p90", "type": "latency", "percentile": 0.90,
         "threshold_ms": 8.0},
        {"name": "goodput", "type": "goodput_floor", "floor_frac": 0.5},
    ]}
    result = evaluate_measured(blob, cfg)
    assert result["ok"]
    assert _obj(result, "p99")["measured"] == 12.5
    assert _obj(result, "p90")["measured"] == 8.0
    assert _obj(result, "goodput")["ok"] is None  # not client-measurable
    strict = evaluate_measured(blob, {"objectives": [
        {"name": "p99", "type": "latency", "percentile": 0.99,
         "threshold_ms": 10.0}]})
    assert not strict["ok"]


@pytest.mark.serve
def test_loadgen_slo_flag_end_to_end(tmp_path, capsys):
    """scripts/loadgen.py --trace --slo: drives an in-process engine with
    traced requests, records per-request trace id + latency, and gates on
    the measured histogram (ISSUE-14 satellite)."""
    import sys

    import jax.numpy as jnp
    import numpy as np

    sys.path.insert(0, str(Path(__file__).parent.parent / "scripts"))
    import loadgen

    from sparse_coding__tpu.models.learned_dict import TiedSAE
    from sparse_coding__tpu.train.checkpoint import save_learned_dicts

    rng = np.random.default_rng(0)
    export = tmp_path / "learned_dicts.pkl"
    save_learned_dicts(export, [(TiedSAE(
        jnp.asarray(rng.standard_normal((64, 16), dtype=np.float32)),
        jnp.zeros((64,)),
    ), {})])
    slo_ok = tmp_path / "slo.json"
    slo_ok.write_text(json.dumps({"objectives": [
        {"name": "avail", "type": "availability", "target": 0.5},
        {"name": "p99", "type": "latency", "percentile": 0.99,
         "threshold_ms": 60_000.0},
    ]}))
    rc = loadgen.main([
        "--export", str(export), "--clients", "2", "--requests", "4",
        "--rows", "2", "--trace", "--slo", str(slo_ok),
    ])
    blob = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert blob["slo"]["ok"]
    per_request = blob["per_request"]
    assert len(per_request) == 8
    assert all(len(r["trace_id"]) == 32 for r in per_request)
    assert all(r["outcome"] == "ok" and r["latency_ms"] > 0
               for r in per_request)
    # a threshold no real encode can meet gates the exit code
    slo_bad = tmp_path / "slo_bad.json"
    slo_bad.write_text(json.dumps({"objectives": [
        {"name": "p99", "type": "latency", "percentile": 0.99,
         "threshold_ms": 0.0001},
    ]}))
    rc = loadgen.main([
        "--export", str(export), "--clients", "1", "--requests", "2",
        "--rows", "2", "--slo", str(slo_bad),
    ])
    capsys.readouterr()
    assert rc == 1
