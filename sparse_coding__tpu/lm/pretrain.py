"""In-image subject-LM pretraining (next-token loss on synthetic corpora).

The reference harvests from downloaded Pythia/GPT-2 checkpoints
(`activation_dataset.py:126-132`); this image has zero egress, so parity
subjects are pretrained HERE, on the chip, on a `data.synthetic_text`
corpus — a few thousand steps take a random-init transformer from ~log(vocab)
nats to near the corpus's ~log(k_succ) entropy bound, giving its activations
genuine contextual structure (VERDICT r2 next #4).

TPU shape: one jitted `lax.scan` over K batches per dispatch (amortizes the
tunnel's ~10 ms dispatch latency, cf. `Ensemble.step_scan`), bf16 compute
with f32 master params/Adam via the same master-weights scheme the SAE
training uses.
"""

from __future__ import annotations

from functools import partial
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from sparse_coding__tpu.lm import model as lm_model


def make_pretrain_scan_step(
    cfg: lm_model.LMConfig,
    tx: optax.GradientTransformation,
    compute_dtype=None,
):
    """`(params, opt_state, tokens[K,B,S]) -> (params, opt_state, losses[K])`,
    one compiled program for K optimizer steps."""

    def loss_fn(p, toks):
        if compute_dtype is not None:
            p = jax.tree.map(
                lambda x: x.astype(compute_dtype)
                if jnp.issubdtype(x.dtype, jnp.floating)
                else x,
                p,
            )
        return lm_model.lm_loss(p, toks, cfg)

    def one(carry, toks):
        params, opt_state = carry
        loss, grads = jax.value_and_grad(loss_fn)(params, toks)
        # grads arrive in compute dtype; the optimizer update runs f32
        grads = jax.tree.map(lambda g: g.astype(jnp.float32), grads)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return (params, opt_state), loss

    @partial(jax.jit, donate_argnums=(0, 1))
    def scan_step(params, opt_state, tokens):
        (params, opt_state), losses = jax.lax.scan(one, (params, opt_state), tokens)
        return params, opt_state, losses

    return scan_step


def pretrain_lm(
    params,
    cfg: lm_model.LMConfig,
    tokens: np.ndarray,
    n_steps: int,
    batch_size: int = 32,
    learning_rate: float = 3e-4,
    scan_steps: int = 8,
    compute_dtype=jnp.bfloat16,
    warmup: int = 100,
    seed: int = 0,
    log_every: int = 0,
) -> Tuple[dict, Dict[str, float]]:
    """Train `params` for `n_steps` of AdamW on `[N, S]` token rows.

    Returns (trained params, {"loss_first", "loss_last"}). Rows are sampled
    with replacement per step; cosine-decayed LR after linear warmup (the
    standard small-LM recipe — nothing exotic, the goal is structured
    activations, not SOTA).
    """
    sched = optax.warmup_cosine_decay_schedule(
        0.0, learning_rate, min(warmup, max(1, n_steps // 10)), max(n_steps, 2)
    )
    tx = optax.adamw(sched, weight_decay=0.01)
    opt_state = tx.init(params)
    step = make_pretrain_scan_step(cfg, tx, compute_dtype)

    rng = np.random.default_rng(seed)
    loss_first: Optional[float] = None
    loss_last = float("nan")
    done = 0
    while done < n_steps:
        k = min(scan_steps, n_steps - done)
        idx = rng.integers(0, tokens.shape[0], (k, batch_size))
        batch = jnp.asarray(tokens[idx])
        params, opt_state, losses = step(params, opt_state, batch)
        done += k
        losses = jax.device_get(losses)
        if loss_first is None:
            loss_first = float(losses[0])
        loss_last = float(losses[-1])
        if log_every and (done % log_every < k):
            print(f"  pretrain step {done}/{n_steps}: loss {loss_last:.3f}")
    return params, {"loss_first": float(loss_first), "loss_last": loss_last}
