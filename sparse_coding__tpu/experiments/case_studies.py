"""Scripted equivalents of the reference's analysis notebooks.

The reference ships five notebook analyses (SURVEY.md §2.5 "Notebooks") with
hard-coded cluster paths; here each is a function over `(LearnedDict,
hyperparams)` exports + the JAX subject LM, so they run headless and are
testable:

  dict_compare            — Hungarian-matched MCS between two dictionaries
                            (`interp_notebooks/dict_compare.ipynb`,
                            `minimal_feature_interp.ipynb`: matched-feature
                            histogram + count above threshold)
  dict_across_time        — matched MCS of each training save point against
                            the final dictionary
                            (`interp_notebooks/dict_across_time.ipynb`)
  inter_layer_mcs         — mean matched MCS between every pair of layers'
                            dictionaries
                            (`experiments/inter_layer_comparison.ipynb`)
  inter_dict_connections  — activation-correlation matrix between two dicts'
                            codes on shared inputs, top connections
                            (`inter_dict_connections.ipynb`)
  feature_case_study      — top-activating fragments with per-token
                            activations + top output-logit tokens for one
                            feature (`case_studies_loop.ipynb`,
                            `interp_notebooks/feature_interp.ipynb`,
                            `minimal_feature_interp.ipynb`)
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from sparse_coding__tpu.metrics.standard import mmcs


def _as_matrix(d) -> jax.Array:
    return d.get_learned_dict() if hasattr(d, "get_learned_dict") else jnp.asarray(d)


def _matched_sims(small: jax.Array, large: jax.Array) -> Tuple[np.ndarray, np.ndarray]:
    """Hungarian 1:1 matching of the smaller dict's atoms into the larger.

    Returns (sims, assignment), BOTH in small-atom order: `sims[k]` is atom
    k's matched cosine and `assignment[k]` the large-dict atom it matched."""
    from scipy.optimize import linear_sum_assignment

    cos = np.asarray(jnp.einsum("sd,ld->sl", small, large))
    rows, cols = linear_sum_assignment(-cos)  # rows == arange(n_small), sorted
    return cos[rows, cols], cols


def dict_compare(dict_a, dict_b, threshold: float = 0.9) -> Dict[str, Any]:
    """Hungarian-matched comparison of two dictionaries.

    `matched_sims[k]` is the k-th SMALLER-dict atom's matched cosine and
    `assignment[k]` the larger-dict atom it matched (1:1). Also reports the
    fraction above `threshold` ("shared features") and plain MMCS both ways.
    """
    a, b = _as_matrix(dict_a), _as_matrix(dict_b)
    small, large = (a, b) if a.shape[0] <= b.shape[0] else (b, a)
    sims, assignment = _matched_sims(small, large)
    return {
        "matched_sims": sims,
        "assignment": assignment,
        "frac_shared": float((sims > threshold).mean()),
        "n_shared": int((sims > threshold).sum()),
        "mmcs_a_to_b": float(mmcs(a, b)),
        "mmcs_b_to_a": float(mmcs(b, a)),
    }


def dict_across_time(
    save_points: Dict[int, Any], threshold: float = 0.9
) -> List[Dict[str, Any]]:
    """Feature stability over training: each save point's dictionary matched
    against the FINAL one. Returns one row per save point with the matched-MCS
    summary (`dict_across_time.ipynb`'s across-checkpoint comparison)."""
    if not save_points:
        return []
    final = _as_matrix(save_points[max(save_points)])
    rows = []
    for k in sorted(save_points):
        m = _as_matrix(save_points[k])
        small, large = (m, final) if m.shape[0] <= final.shape[0] else (final, m)
        sims, _ = _matched_sims(small, large)
        rows.append(
            {
                "save_point": k,
                "mean_matched_mcs": float(sims.mean()),
                "frac_shared": float((sims > threshold).mean()),
            }
        )
    return rows


def inter_layer_mcs(dicts_by_layer: Dict[int, Any]) -> Tuple[np.ndarray, List[int]]:
    """Mean matched MCS between every pair of layers' dictionaries
    (`inter_layer_comparison.ipynb`: do features persist across the residual
    stream?). Returns (symmetric [L, L] matrix, layer order)."""
    layers = sorted(dicts_by_layer)
    mats = [_as_matrix(dicts_by_layer[l]) for l in layers]
    n = len(layers)
    out = np.eye(n, dtype=np.float64)
    for i in range(n):
        for j in range(i + 1, n):
            a, b = mats[i], mats[j]
            small, large = (a, b) if a.shape[0] <= b.shape[0] else (b, a)
            sims, _ = _matched_sims(small, large)
            out[i, j] = out[j, i] = float(sims.mean())
    return out, layers


def inter_dict_connections(
    dict_up,
    dict_down,
    acts_up: jax.Array,
    acts_down: jax.Array,
    top_k: int = 10,
    eps: float = 1e-8,
) -> Dict[str, Any]:
    """Correlation of two dictionaries' feature activations on shared inputs
    (`inter_dict_connections.ipynb`): which upstream features co-fire with
    which downstream ones. `acts_up`/`acts_down` are the SAME datapoints'
    activations at the two hook points, row-aligned.

    Returns the [n_up, n_down] Pearson matrix and the top-k strongest
    (upstream, downstream, r) connections.
    """
    assert acts_up.shape[0] == acts_down.shape[0], "row-aligned inputs required"
    cu = np.asarray(dict_up.encode(dict_up.center(acts_up)), dtype=np.float64)
    cd = np.asarray(dict_down.encode(dict_down.center(acts_down)), dtype=np.float64)
    cu = (cu - cu.mean(0)) / (cu.std(0) + eps)
    cd = (cd - cd.mean(0)) / (cd.std(0) + eps)
    corr = cu.T @ cd / cu.shape[0]
    flat = np.argsort(-np.abs(corr), axis=None)[:top_k]
    ups, downs = np.unravel_index(flat, corr.shape)
    top = [(int(u), int(d), float(corr[u, d])) for u, d in zip(ups, downs)]
    return {"correlation": corr, "top_connections": top}


from functools import partial


@partial(jax.jit, static_argnums=2)
def _encode_one_feature(ld, acts, feature):
    """One feature's per-token activations, in the dict's centered basis
    (encode∘center, the canonical path used by `LearnedDict.predict` and the
    metric library)."""
    B, L, C = acts.shape
    c = ld.encode(ld.center(acts.reshape(B * L, C)))
    return c.reshape(B, L, -1)[:, :, feature]


def feature_case_study(
    params,
    lm_cfg,
    learned_dict,
    layer: int,
    layer_loc: str,
    fragments: np.ndarray,
    decode_tokens: Callable[[Sequence[int]], List[str]],
    feature: int,
    n_top_fragments: int = 5,
    n_top_logits: int = 10,
    batch_size: int = 32,
) -> Dict[str, Any]:
    """One feature's story (`case_studies_loop.ipynb` /
    `feature_interp.ipynb`): top-activating fragments with per-token
    activations, plus the feature direction's top output-logit tokens
    (direction @ unembed — only for residual-stream dicts, where the
    direction lives in the unembed's input space).

    Returns {"fragments": [(tokens, activations)...], "top_logit_tokens":
    [(token_id, logit)...] or None}.
    """
    from sparse_coding__tpu.interp.pipeline import _jitted_fragment_capture

    if not 0 <= feature < learned_dict.n_feats:
        raise ValueError(
            f"feature {feature} out of range for a {learned_dict.n_feats}-feature "
            "dict (JAX would silently clamp the index)"
        )
    capture = _jitted_fragment_capture(lm_cfg, layer, layer_loc)
    n_frags, frag_len = fragments.shape
    pad = (-n_frags) % batch_size
    padded = (
        np.concatenate([fragments, np.zeros((pad, frag_len), fragments.dtype)])
        if pad
        else fragments
    )
    acts_per_frag = []
    for start in range(0, padded.shape[0], batch_size):
        acts = capture(params, jnp.asarray(padded[start : start + batch_size]))
        codes = _encode_one_feature(learned_dict, acts, feature)
        acts_per_frag.append(np.asarray(jax.device_get(codes)))
    per_tok = np.concatenate(acts_per_frag)[:n_frags]  # [n_frags, frag_len]

    order = np.argsort(-per_tok.max(axis=1))[:n_top_fragments]
    frags = [
        (decode_tokens(fragments[i]), [float(a) for a in per_tok[i]]) for i in order
    ]

    top_logits: Optional[List[Tuple[int, float]]] = None
    if layer_loc == "residual":
        # logit lens: residual directions live in the unembed's input space;
        # tied-embedding models unembed with params["embed"] (lm.model's
        # forward does exactly this)
        unembed = (
            params.get("embed")
            if getattr(lm_cfg, "tie_word_embeddings", False)
            else params.get("unembed")
        )
        if unembed is not None:
            direction = learned_dict.get_learned_dict()[feature]
            logits = np.asarray(jnp.asarray(unembed) @ direction)
            top_ids = np.argsort(-logits)[:n_top_logits]
            top_logits = [(int(t), float(logits[t])) for t in top_ids]
    return {"fragments": frags, "top_logit_tokens": top_logits}


def render_case_study(study: Dict[str, Any], decode_token: Optional[Callable[[int], str]] = None) -> str:
    """Plain-text rendering of a `feature_case_study` (the notebook's
    circuitsvis HTML, minus the HTML): tokens annotated with activations."""
    lines = []
    for toks, acts in study["fragments"]:
        peak = max(acts) or 1.0
        lines.append(
            " ".join(
                f"[{t}|{a:.1f}]" if a > 0.1 * peak else t
                for t, a in zip(toks, acts)
            )
        )
    if study["top_logit_tokens"]:
        shown = [
            decode_token(t) if decode_token else str(t)
            for t, _ in study["top_logit_tokens"]
        ]
        lines.append("top output tokens: " + ", ".join(shown))
    return "\n".join(lines)
